"""Optimizers + LR schedules used by the five workloads.

Covers the reference's optimizer surface (SURVEY.md §2a): plain SGD/Adam
(MNIST), SGD+momentum with step/cosine LR (ResNets), LARS (ResNet-50 large
batch), AdamW with warmup-linear-decay (BERT), AdamW with warmup-cosine
(GPT-2) — all as optax chains so they compose with clipping and grad
accumulation inside the single compiled step.
"""

from __future__ import annotations

import optax

from tensorflow_examples_tpu.train.config import TrainConfig


def _updates(cfg: TrainConfig, steps: int) -> int:
    """Convert a micro-step count to optimizer-update count.

    Schedules live inside the optax chain, which under ``MultiSteps``
    ticks once per APPLIED update (every grad_accum_steps micro-steps) —
    so config horizons, given in loop steps, are rescaled here."""
    return max(steps // max(cfg.grad_accum_steps, 1), 1)


def warmup_cosine(cfg: TrainConfig, *, end_value: float = 0.0) -> optax.Schedule:
    warmup = _updates(cfg, max(cfg.warmup_steps, 1))
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=warmup,
        # decay_steps includes warmup; keep the cosine span positive even
        # for short smoke runs where train_steps < warmup_steps.
        decay_steps=max(_updates(cfg, cfg.train_steps), warmup + 1, 2),
        end_value=end_value,
    )


def warmup_linear(cfg: TrainConfig) -> optax.Schedule:
    """BERT fine-tune schedule: linear warmup then linear decay to 0."""
    warmup = _updates(cfg, max(cfg.warmup_steps, 1))
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, cfg.learning_rate, warmup),
            optax.linear_schedule(
                cfg.learning_rate,
                0.0,
                max(_updates(cfg, cfg.train_steps) - warmup, 1),
            ),
        ],
        boundaries=[warmup],
    )


def _maybe_wrap(cfg: TrainConfig, tx: optax.GradientTransformation):
    parts = []
    if cfg.grad_clip_norm > 0:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    parts.append(tx)
    tx = optax.chain(*parts) if len(parts) > 1 else tx
    if cfg.grad_accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=cfg.grad_accum_steps)
    return tx


def adam(cfg: TrainConfig) -> optax.GradientTransformation:
    return _maybe_wrap(cfg, optax.adam(cfg.learning_rate))


def adamw_cosine(cfg: TrainConfig) -> optax.GradientTransformation:
    return _maybe_wrap(
        cfg,
        optax.adamw(
            warmup_cosine(cfg, end_value=0.1 * cfg.learning_rate),
            b1=0.9,
            b2=0.95,
            weight_decay=cfg.weight_decay,
        ),
    )


def adamw_linear(cfg: TrainConfig) -> optax.GradientTransformation:
    return _maybe_wrap(
        cfg,
        optax.adamw(
            warmup_linear(cfg),
            b1=0.9,
            b2=0.999,
            eps=1e-6,
            weight_decay=cfg.weight_decay,
        ),
    )


def sgd_momentum_cosine(cfg: TrainConfig, *, nesterov: bool = True):
    return _maybe_wrap(
        cfg,
        optax.chain(
            optax.add_decayed_weights(cfg.weight_decay)
            if cfg.weight_decay
            else optax.identity(),
            optax.sgd(warmup_cosine(cfg), momentum=0.9, nesterov=nesterov),
        ),
    )


def lars(cfg: TrainConfig) -> optax.GradientTransformation:
    """LARS for large-batch ResNet-50 (SURVEY.md §2a row 3)."""
    return _maybe_wrap(
        cfg,
        optax.lars(
            warmup_cosine(cfg),
            weight_decay=cfg.weight_decay,
            momentum=0.9,
        ),
    )
