"""Task: what a workload must define to run on the shared loop.

A Task is the TPU-native replacement for an entire reference example
script: the model, how to compute its loss/metrics, how its params shard,
and its optimizer. Everything else (distribution, input feeding, stepping,
checkpointing, logging) lives in the shared Trainer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import jax
import optax

from tensorflow_examples_tpu.core.sharding import REPLICATED, ShardingRules
from tensorflow_examples_tpu.train.config import TrainConfig

Batch = Mapping[str, jax.Array]
# loss_fn(params, model_state, batch, rng=, train=)
#   -> (loss, metrics-dict, new_model_state)
LossFn = Callable[..., tuple[jax.Array, Mapping[str, jax.Array], Any]]


@dataclasses.dataclass
class Task:
    name: str
    # init_fn(rng) -> flax-style variables pytree: {"params": …, then any
    # non-trainable collections ("batch_stats", …) which become
    # TrainState.model_state}
    init_fn: Callable[[jax.Array], Any]
    loss_fn: LossFn
    make_optimizer: Callable[[TrainConfig], optax.GradientTransformation]
    sharding_rules: ShardingRules = dataclasses.field(default_factory=lambda: REPLICATED)
    # eval_fn(params, model_state, batch) -> metrics dict; a "weight" entry
    # weights the mean (padded-batch masking)
    eval_fn: Callable[..., Mapping[str, jax.Array]] | None = None
    # eval_finalize(mean-metrics dict) -> final dict; for metrics that are
    # functions of globally-aggregated means rather than batch means
    # (F1/MCC from confusion rates, Pearson from moment means).
    eval_finalize: Callable[[dict], dict] | None = None
