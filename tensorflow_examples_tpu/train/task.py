"""Task: what a workload must define to run on the shared loop.

A Task is the TPU-native replacement for an entire reference example
script: the model, how to compute its loss/metrics, how its params shard,
and its optimizer. Everything else (distribution, input feeding, stepping,
checkpointing, logging) lives in the shared Trainer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import jax
import optax

from tensorflow_examples_tpu.core.sharding import REPLICATED, ShardingRules
from tensorflow_examples_tpu.train.config import TrainConfig

Batch = Mapping[str, jax.Array]
# loss_fn(params, batch, model_apply, rng, train) -> (loss, metrics-dict)
LossFn = Callable[..., tuple[jax.Array, Mapping[str, jax.Array]]]


@dataclasses.dataclass
class Task:
    name: str
    # init_fn(rng) -> params pytree
    init_fn: Callable[[jax.Array], Any]
    # apply_fn(params, batch, rng, train) -> (loss, metrics)
    loss_fn: LossFn
    make_optimizer: Callable[[TrainConfig], optax.GradientTransformation]
    sharding_rules: ShardingRules = dataclasses.field(default_factory=lambda: REPLICATED)
    # eval_step(params, batch) -> metrics dict of (sum, count) style values
    eval_fn: Callable[..., Mapping[str, jax.Array]] | None = None
