"""Training layer: the ONE shared loop all five workloads run through.

The reference repeated ~150 lines of custom training loop per example
(iterate dist dataset → strategy.run(step) → reduce → log → ckpt;
SURVEY.md §2b/§3). Here that machinery exists once: a jitted train step
(forward/backward/collectives/update in a single XLA program), an eval
loop, orbax checkpointing, and clu metric writers, parameterized by a
``Task`` (model + loss + metrics) and a ``TrainConfig``.
"""

from tensorflow_examples_tpu.train.config import TrainConfig
from tensorflow_examples_tpu.train.state import TrainState
from tensorflow_examples_tpu.train.task import Task
from tensorflow_examples_tpu.train.loop import Trainer
