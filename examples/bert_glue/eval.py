#!/usr/bin/env python
"""BERT GLUE eval CLI: restore checkpoint → per-task metrics
(accuracy; +F1 for MRPC/QQP, MCC for CoLA, Pearson for STS-B).

    python examples/bert_glue/eval.py --device=tpu --task=sst2 --workdir=/path/to/run
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import eval_main
from tensorflow_examples_tpu.workloads import bert_glue

if __name__ == "__main__":
    app.run(eval_main(bert_glue, bert_glue.BertGlueConfig()))
