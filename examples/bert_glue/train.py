#!/usr/bin/env python
"""BERT-base GLUE fine-tune CLI (BASELINE.json:configs[3]).

Usage (contract preserved from the reference — BASELINE.json:north_star):
    python examples/bert_glue/train.py --device=tpu --task=sst2 \
        --pretrained=/models/bert-base-uncased [--data_dir=...]

--data_dir expects pre-tokenized <task>_<split>.npz (see
data/sources.load_glue); omit for synthetic data. Multi-host runs use the
same command per host (core/distributed.py bootstraps from TPU metadata).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import train_main
from tensorflow_examples_tpu.workloads import bert_glue

if __name__ == "__main__":
    app.run(train_main(bert_glue, bert_glue.BertGlueConfig()))
