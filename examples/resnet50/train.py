#!/usr/bin/env python
"""ResNet-50 ImageNet training CLI (BASELINE.json:configs[2]).

Usage (contract preserved from the reference — BASELINE.json:north_star):
    python examples/resnet50/train.py --device=tpu \
        --data_dir=/data/imagenet [--global_batch_size=1024 ...]

--data_dir expects standard ImageNet TFRecord shards (train-*,
validation-*); omit it for a synthetic smoke stream. Large-batch runs:
--optimizer=lars.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import train_main
from tensorflow_examples_tpu.workloads import imagenet

if __name__ == "__main__":
    app.run(train_main(imagenet, imagenet.ImagenetConfig()))
