#!/usr/bin/env python
"""ResNet-50 ImageNet eval CLI: restore checkpoint → top-1/top-5.

    python examples/resnet50/eval.py --device=tpu --workdir=/path/to/run \
        --data_dir=/data/imagenet
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import eval_main
from tensorflow_examples_tpu.workloads import imagenet

if __name__ == "__main__":
    app.run(eval_main(imagenet, imagenet.ImagenetConfig()))
