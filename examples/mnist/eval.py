#!/usr/bin/env python
"""MNIST MLP eval CLI: restore latest checkpoint → test-set metrics.

    python examples/mnist/eval.py --device=tpu --workdir=/path/to/run
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import eval_main
from tensorflow_examples_tpu.workloads import mnist

if __name__ == "__main__":
    app.run(eval_main(mnist, mnist.MnistConfig()))
