#!/usr/bin/env python
"""MNIST MLP eval CLI: restore latest checkpoint → test-set metrics.

    python examples/mnist/eval.py --device=tpu --workdir=/path/to/run
"""

from absl import app, logging

from tensorflow_examples_tpu.core import distributed
from tensorflow_examples_tpu.data.memory import eval_batches
from tensorflow_examples_tpu.train.checkpoint import CheckpointManager
from tensorflow_examples_tpu.train.config import (
    apply_device_flag,
    config_from_flags,
    define_flags_from_config,
)
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import mnist

_DEFAULT = mnist.MnistConfig()
define_flags_from_config(_DEFAULT)


def main(argv):
    del argv
    logging.set_verbosity(logging.INFO)
    cfg = config_from_flags(_DEFAULT)
    apply_device_flag(cfg.device)
    distributed.initialize()
    if not cfg.workdir:
        raise app.UsageError("--workdir is required for eval")

    _, test_ds = mnist.datasets(cfg)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    restored = CheckpointManager(cfg.workdir).restore_latest(trainer.state)
    if restored is None:
        raise SystemExit(f"no checkpoint under {cfg.workdir}")
    trainer.state = restored[0]
    eval_bs = cfg.eval_batch_size or cfg.global_batch_size
    metrics = trainer.evaluate(eval_batches(test_ds, eval_bs))
    print({k: round(v, 4) for k, v in metrics.items()})


if __name__ == "__main__":
    app.run(main)
