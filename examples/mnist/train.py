#!/usr/bin/env python
"""MNIST MLP training CLI (BASELINE.json:configs[0]).

Usage (contract preserved from the reference — BASELINE.json:north_star):
    python examples/mnist/train.py --device=tpu [--train_steps=N ...]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import train_main
from tensorflow_examples_tpu.workloads import mnist

if __name__ == "__main__":
    app.run(train_main(mnist, mnist.MnistConfig()))
