#!/usr/bin/env python
"""MNIST MLP training CLI.

Usage (contract preserved from the reference — BASELINE.json:north_star):
    python examples/mnist/train.py --device=tpu [--train_steps=N ...]
"""

import sys

from absl import app, flags, logging

from tensorflow_examples_tpu.core import distributed
from tensorflow_examples_tpu.data.memory import eval_batches, train_iterator
from tensorflow_examples_tpu.train.config import (
    apply_device_flag,
    config_from_flags,
    define_flags_from_config,
)
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import mnist

_DEFAULT = mnist.MnistConfig()
define_flags_from_config(_DEFAULT)


def main(argv):
    del argv
    logging.set_verbosity(logging.INFO)
    cfg = config_from_flags(_DEFAULT)
    apply_device_flag(cfg.device)
    distributed.initialize()

    train_ds, test_ds = mnist.datasets(cfg)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    eval_bs = cfg.eval_batch_size or cfg.global_batch_size
    metrics = trainer.fit(
        lambda start: train_iterator(
            train_ds, cfg.global_batch_size, seed=cfg.seed, start_step=start
        ),
        eval_iter_fn=lambda: eval_batches(test_ds, eval_bs),
    )
    print({k: round(v, 4) for k, v in metrics.items()})


if __name__ == "__main__":
    app.run(main)
