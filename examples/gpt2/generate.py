#!/usr/bin/env python
"""GPT-2 sampling CLI (the reference's eval.py sampling path).

    python examples/gpt2/generate.py --workdir=/path/to/run \
        --num_tokens=64 --temperature=0.8 --top_k=40

Decodes through the static-shape KV cache (models/transformer.py).
--prompt is text when a BPE vocab is available (--vocab_dir, or
vocab.json/merges.txt in --data_dir as written by tools/prepare_lm.py)
or with byte-level corpora (vocab_size=256); otherwise supply
comma-separated token ids via --prompt_ids.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np
from absl import app, flags

from tensorflow_examples_tpu.models import transformer
from tensorflow_examples_tpu.train.checkpoint import CheckpointManager
from tensorflow_examples_tpu.train.cli import _setup
from tensorflow_examples_tpu.train.config import define_flags_from_config
from tensorflow_examples_tpu.train.loop import state_factory
from tensorflow_examples_tpu.workloads import gpt2

define_flags_from_config(gpt2.Gpt2Config())
flags.DEFINE_integer("num_tokens", 64, "tokens to sample")
flags.DEFINE_float("temperature", 0.8, "0 = greedy")
flags.DEFINE_integer("top_k", 40, "0 disables top-k filtering")
flags.DEFINE_string("prompt", "The ", "text prompt")
flags.DEFINE_string("prompt_ids", "", "comma-separated token ids")
flags.DEFINE_string("vocab_dir", "", "dir with vocab.json+merges.txt")
FLAGS = flags.FLAGS


def _load_tokenizer(cfg):
    """BPE tokenizer from --vocab_dir or --data_dir, if vendored there."""
    from tensorflow_examples_tpu.data.tokenizers import ByteLevelBPE

    for d in (FLAGS.vocab_dir, cfg.data_dir):
        if d and os.path.exists(os.path.join(d, "vocab.json")):
            return ByteLevelBPE.from_dir(d)
    return None


def main(argv):
    del argv
    import jax
    import jax.numpy as jnp

    cfg = _setup(gpt2, gpt2.Gpt2Config())
    if not cfg.workdir:
        raise app.UsageError("--workdir is required for generate")
    # Restore through an eval_shape template: no throwaway random params
    # or optimizer state ever materialize on the chip.
    make_state, _ = state_factory(gpt2.make_task(cfg), cfg)
    abstract = jax.eval_shape(make_state, jax.random.PRNGKey(0))
    restored = CheckpointManager(cfg.workdir).restore_latest(abstract)
    if restored is None:
        raise SystemExit(f"no checkpoint under {cfg.workdir}")
    params = jax.tree.map(jnp.asarray, restored[0].params)

    tokenizer = _load_tokenizer(cfg)
    if FLAGS.prompt_ids:
        ids = [int(t) for t in FLAGS.prompt_ids.split(",")]
    elif tokenizer is not None:
        ids = tokenizer.encode(FLAGS.prompt)
    else:
        ids = list(FLAGS.prompt.encode())
    prompt = np.asarray([ids], np.int32)

    model = transformer.Transformer(gpt2.model_config(cfg))
    out = transformer.generate(
        model,
        params,
        prompt,
        num_tokens=FLAGS.num_tokens,
        rng=jax.random.PRNGKey(cfg.seed),
        temperature=FLAGS.temperature,
        top_k=FLAGS.top_k,
    )
    toks = np.asarray(out[0])
    print("token ids:", toks.tolist())
    if tokenizer is not None:
        print(tokenizer.decode(toks))
    elif cfg.vocab_size <= 256:
        print(bytes(np.clip(toks, 0, 255).astype(np.uint8)).decode(errors="replace"))


if __name__ == "__main__":
    app.run(main)
