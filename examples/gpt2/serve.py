#!/usr/bin/env python
"""GPT-2 serving CLI: checkpoint -> live /generate endpoint.

    python examples/gpt2/serve.py --workdir=/path/to/run --port=8000 \
        --max_slots=8

    curl -s localhost:8000/generate -d \
        '{"text": "The ", "max_new_tokens": 32, "temperature": 0.8}'

Loads the latest checkpoint (same eval_shape-template restore as
generate.py), warms up the serving engine's whole bucket ladder (the
AOT pass — steady state is zero-recompile, watch
``post_warmup_recompiles`` on ``/health``), starts the continuous
batcher and the HTTP frontend, and serves until SIGTERM — which drains
in-flight requests, 503s new ones, and exits 0 (the same preemption
contract as training; a second signal force-quits).

Text in/out uses a BPE vocab (--vocab_dir, or vocab.json/merges.txt
in --data_dir), falling back to raw bytes for byte-level corpora
(vocab_size <= 256, same rule as generate.py); otherwise send token
ids as "prompt". A schema-v4
``kind="serving"`` stats line is appended to ``workdir/serving.jsonl``
every ``--stats_every`` seconds (the serving counterpart of training's
``metrics.jsonl`` — same JSONL discipline, ``/window`` serves the
latest line). The same tick samples the in-process time-series store
(ISSUE 19), so ``GET /series`` serves ring-buffered instrument history
with p50/p95/p99 rollups.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app, flags

from tensorflow_examples_tpu.train.checkpoint import CheckpointManager
from tensorflow_examples_tpu.train.cli import _setup
from tensorflow_examples_tpu.train.config import define_flags_from_config
from tensorflow_examples_tpu.train.loop import state_factory
from tensorflow_examples_tpu.workloads import gpt2

define_flags_from_config(gpt2.Gpt2Config())
flags.DEFINE_integer("port", 8000, "HTTP port (0 = auto-assign)")
flags.DEFINE_integer("max_slots", 8, "concurrent decode slots")
flags.DEFINE_integer("max_queue", 64, "bounded submit queue (then 503)")
flags.DEFINE_float("max_delay_s", 0.002, "idle burst-coalescing window")
flags.DEFINE_float("serve_watchdog_secs", 60.0,
                   "serve-loop hang detection (0 disables)")
flags.DEFINE_float("stats_every", 10.0,
                   "seconds between serving.jsonl stats lines (0 disables)")
flags.DEFINE_integer(
    "kv_block_size", 0,
    "paged KV block size (docs/serving.md; 0 = dense pool). Power of "
    "two dividing the bucket floors and max_len; slot capacity then "
    "scales with used tokens and shared prompt prefixes prefill once.")
flags.DEFINE_integer(
    "kv_blocks", 0,
    "physical KV blocks (0 = dense-equivalent worst case); shrink to "
    "bank the memory paging saves — exhaustion sheds load loudly (503)")
flags.DEFINE_string(
    "kv_dtype", "",
    "KV cache storage dtype: '' (cache dtype), 'int8', or 'fp8' "
    "(per-block scales; bounded-divergence modes — require "
    "--kv_block_size; fp8 needs backend float8 support)")
flags.DEFINE_string(
    "weight_dtype", "",
    "weight-only quantization (docs/serving.md quantization section): "
    "'' serves the checkpoint's dtype; 'int8'/'fp8' quantize every "
    "matmul weight at load time via the precision registry — HBM "
    "param bytes drop ~4x, dequant happens inside the compiled "
    "matmuls, streams are bounded-divergence vs f32 (serve_bench "
    "--weight-dtype banks the gate record). Composes with "
    "workdir/sharding.json: quantized payloads shard by the weight's "
    "rule, scales inherit their weight's spec.")
flags.DEFINE_boolean(
    "prefix_cache", True,
    "reuse immutable full prompt blocks across requests (paged only)")
flags.DEFINE_integer(
    "spec_decode_k", 0,
    "speculative decoding draft window (docs/serving.md): verify up to "
    "K drafted tokens per decode step. Output streams stay "
    "token-identical — K buys TPOT on prompt-like text, never changes "
    "tokens. 0 disables.")
flags.DEFINE_integer(
    "draft_ngram", 3,
    "longest n-gram the self-speculative drafter matches against the "
    "request's own context (spec_decode_k > 0 only)")
flags.DEFINE_string(
    "decode_attention", "",
    "decode attention impl: '' (engine default), 'xla' (gather "
    "reference), 'flash' (Pallas prefill attend), or 'paged_flash' "
    "(fused paged-decode kernel; requires --kv_block_size)")
flags.DEFINE_string(
    "role", "mixed",
    "fleet scheduling role (docs/serving.md scheduling section): "
    "'mixed' (default — serves everything), 'prefill' (runs prompts to "
    "completion-of-prefill and exports KV pages), or 'decode' (imports "
    "pages and continues streams). Advisory: every role still answers "
    "a full /generate. Published on /health for the router.")
flags.DEFINE_integer(
    "prefill_chunk_tokens", 0,
    "chunked prefill admission (docs/serving.md): split any cold "
    "prompt tail longer than this into block-aligned chunks run one "
    "per decode-loop iteration, so a long prefill interleaves with "
    "decode steps. Requires --kv_block_size (+ prefix_cache) and must "
    "be a multiple of it. 0 disables.")
flags.DEFINE_boolean(
    "brownout", False,
    "overload brownout ladder (docs/serving.md overload section): "
    "under pressure shed batch -> cap max_new_tokens -> skip "
    "speculation -> shed interactive, stepped with hysteresis; the "
    "level is published on /health for the router and autoscaler.")
flags.DEFINE_integer(
    "brownout_queue_hi", 0,
    "brownout queue-depth high watermark (0 = 2 * max_slots)")
flags.DEFINE_float(
    "brownout_hold_s", 0.5,
    "brownout hysteresis: min dwell per rung up, sustained-clear "
    "time per rung down")
flags.DEFINE_integer(
    "brownout_max_new_tokens", 8,
    "brownout level-2 generation cap (streams retire early as a "
    "prefix, truncated='brownout')")
flags.DEFINE_string("vocab_dir", "", "dir with vocab.json+merges.txt")
flags.DEFINE_string(
    "serve_sharding_config", "",
    "ShardingConfig JSON for sharded serving (docs/sharding.md); "
    "default: auto-load <workdir>/sharding.json — the config the "
    "training run persisted — falling back to replicated params. "
    "'off' forces replicated placement.")
FLAGS = flags.FLAGS


class _ByteTokenizer:
    """generate.py's byte-level text fallback (vocab_size <= 256) with
    the encode/decode surface the frontend expects of a tokenizer."""

    def encode(self, text):
        return list(text.encode())

    def decode(self, tokens):
        return bytes(
            min(max(int(t), 0), 255) for t in tokens
        ).decode(errors="replace")


def _load_tokenizer(cfg):
    from tensorflow_examples_tpu.data.tokenizers import ByteLevelBPE

    for d in (FLAGS.vocab_dir, cfg.data_dir):
        if d and os.path.exists(os.path.join(d, "vocab.json")):
            return ByteLevelBPE.from_dir(d)
    return _ByteTokenizer() if cfg.vocab_size <= 256 else None


def main(argv):
    del argv
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.serving import (
        ContinuousBatcher,
        InferenceEngine,
        ServeConfig,
        ServingFrontend,
        run_until_preempted,
    )

    cfg = _setup(gpt2, gpt2.Gpt2Config())
    if not cfg.workdir:
        raise app.UsageError("--workdir is required for serve")

    # One ShardingConfig drives train AND serve (docs/sharding.md): the
    # trainer persisted its placement spec next to the checkpoints;
    # serving places the restored params + KV pool by the same rules
    # instead of replicating. --serve_sharding_config overrides (or
    # 'off' disables). Resolved BEFORE the restore so the checkpoint
    # deserializes STRAIGHT into the sharded layout — a model that only
    # fits split must never materialize on one device.
    from tensorflow_examples_tpu.models.transformer import GPT2_RULES
    from tensorflow_examples_tpu.sharding import ShardingConfig

    sharding = None
    src = FLAGS.serve_sharding_config
    if src != "off":
        path = src or os.path.join(cfg.workdir, "sharding.json")
        if src or os.path.exists(path):
            import dataclasses as _dc

            sharding = ShardingConfig.load(path)
            # Serving has no data parallelism within one process — a
            # training config's data axis would only replicate params
            # over devices serving never uses (and make a pod-trained
            # config unserveable on a single chip). Collapse it.
            sharding = _dc.replace(
                sharding, mesh={**sharding.mesh, "data": 1}
            )
            try:
                sharding.build_mesh()
            except ValueError as e:
                if src:
                    # Explicitly requested config: fail loudly.
                    raise
                # Auto-loaded from the workdir: a host too small for
                # the training layout serves replicated, as before.
                print(
                    f"sharding config {path} does not fit this host "
                    f"({e}); serving with replicated params",
                    file=sys.stderr,
                )
                sharding = None
            else:
                print(f"sharding config: {path}", file=sys.stderr)

    make_state, _ = state_factory(gpt2.make_task(cfg), cfg)
    abstract = jax.eval_shape(make_state, jax.random.PRNGKey(0))
    if sharding is not None:
        # Shardings on the WHOLE template — params by the rules, the
        # optimizer moments inheriting them — so nothing (the Adam
        # state is 2x the param bytes) ever lands whole on one device.
        from tensorflow_examples_tpu.sharding import state_shardings

        mesh = sharding.build_mesh()
        sh = state_shardings(
            abstract,
            mesh,
            sharding.sharding_rules(default=GPT2_RULES),
            zero1=sharding.zero1,
            batch_axes=sharding.batch_axes,
        )
        abstract = jax.tree.map(
            lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                              sharding=s),
            abstract,
            sh,
        )
    restored = CheckpointManager(cfg.workdir).restore_latest(abstract)
    if restored is None:
        raise SystemExit(f"no checkpoint under {cfg.workdir}")
    # Already placed when sharded (the engine's device_put is then a
    # no-op); asarray only on the replicated path.
    params = (
        restored[0].params
        if sharding is not None
        else jax.tree.map(jnp.asarray, restored[0].params)
    )

    engine = InferenceEngine(
        gpt2.model_config(cfg),
        params,
        cfg=ServeConfig(
            max_slots=FLAGS.max_slots,
            max_queue=FLAGS.max_queue,
            max_delay_s=FLAGS.max_delay_s,
            watchdog_secs=FLAGS.serve_watchdog_secs,
            kv_block_size=FLAGS.kv_block_size,
            kv_blocks=FLAGS.kv_blocks,
            kv_dtype=FLAGS.kv_dtype,
            weight_dtype=FLAGS.weight_dtype,
            prefix_cache=FLAGS.prefix_cache,
            spec_decode_k=FLAGS.spec_decode_k,
            draft_ngram=FLAGS.draft_ngram,
            role=FLAGS.role,
            prefill_chunk_tokens=FLAGS.prefill_chunk_tokens,
            brownout=FLAGS.brownout,
            brownout_queue_hi=FLAGS.brownout_queue_hi,
            brownout_hold_s=FLAGS.brownout_hold_s,
            brownout_max_new_tokens=FLAGS.brownout_max_new_tokens,
            **(
                {"attention": FLAGS.decode_attention}
                if FLAGS.decode_attention else {}
            ),
        ),
        sharding=sharding,
    )
    t0 = time.perf_counter()
    engine.warmup()
    print(
        f"warm: {engine.expected_compiles()} programs in "
        f"{time.perf_counter() - t0:.1f}s; serving from step "
        f"{restored[1]}",
        file=sys.stderr,
    )

    batcher = ContinuousBatcher(engine).start()
    frontend = ServingFrontend(
        batcher, port=FLAGS.port, tokenizer=_load_tokenizer(cfg)
    ).start()
    print(f"listening on :{frontend.port} (POST /generate)", file=sys.stderr)

    if FLAGS.stats_every > 0:
        stats_path = os.path.join(cfg.workdir, "serving.jsonl")

        def stats_loop():
            while not batcher._stop.is_set():
                time.sleep(FLAGS.stats_every)
                # One stats tick = one time-series ring sample
                # (ISSUE 19): GET /series history accrues on exactly
                # the cadence the stats line does.
                frontend.series.sample()
                with open(stats_path, "a") as f:
                    f.write(json.dumps(batcher.stats_line()) + "\n")

        threading.Thread(
            target=stats_loop, name="serving-stats", daemon=True
        ).start()

    raise SystemExit(run_until_preempted(frontend))


if __name__ == "__main__":
    app.run(main)
