#!/usr/bin/env python
"""GPT-2 eval CLI: restore latest checkpoint → validation NLL.

    python examples/gpt2/eval.py --device=tpu --workdir=/path/to/run

Perplexity = exp(nll). For sampling, see generate.py in this directory.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import eval_main
from tensorflow_examples_tpu.workloads import gpt2

if __name__ == "__main__":
    app.run(eval_main(gpt2, gpt2.Gpt2Config()))
