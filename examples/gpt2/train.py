#!/usr/bin/env python
"""GPT-2 124M causal-LM training CLI (BASELINE.json:configs[4]).

Usage (contract preserved from the reference — BASELINE.json:north_star):
    python examples/gpt2/train.py --device=tpu [--train_steps=N ...]

Scale knobs (framework-native — SURVEY.md §2d):
    --mesh_model=4            tensor parallelism over the `model` axis
    --mesh_context=4 --attention=ring   ring-attention sequence parallelism
    --mesh_fsdp=8             ZeRO-style parameter sharding
    --remat --grad_accum_steps=K        memory relief for long context
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import train_main
from tensorflow_examples_tpu.workloads import gpt2

if __name__ == "__main__":
    app.run(train_main(gpt2, gpt2.Gpt2Config()))
