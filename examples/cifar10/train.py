#!/usr/bin/env python
"""CIFAR-10 ResNet-20 training CLI (BASELINE.json:configs[1]).

    python examples/cifar10/train.py --device=tpu [--train_steps=N ...]
"""

from absl import app

from tensorflow_examples_tpu.train.cli import train_main
from tensorflow_examples_tpu.workloads import cifar10

if __name__ == "__main__":
    app.run(train_main(cifar10, cifar10.Cifar10Config()))
