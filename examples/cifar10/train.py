#!/usr/bin/env python
"""CIFAR-10 ResNet-20 training CLI (BASELINE.json:configs[1]).

    python examples/cifar10/train.py --device=tpu [--train_steps=N ...]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import train_main
from tensorflow_examples_tpu.workloads import cifar10

if __name__ == "__main__":
    app.run(train_main(cifar10, cifar10.Cifar10Config()))
