#!/usr/bin/env python
"""CIFAR-10 ResNet-20 eval CLI: restore latest checkpoint → test metrics.

    python examples/cifar10/eval.py --device=tpu --workdir=/path/to/run
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from absl import app

from tensorflow_examples_tpu.train.cli import eval_main
from tensorflow_examples_tpu.workloads import cifar10

if __name__ == "__main__":
    app.run(eval_main(cifar10, cifar10.Cifar10Config()))
