// Threaded JPEG decode + crop + resize + flip + normalize (ctypes ABI).
//
// Round-4 verdict: the ResNet-50 input-fed bench is host-bound and the
// decode stage still ran in the tf.data graph while only normalize ran
// in native/fastdata.cpp (VERDICT r4 weak #2). This library makes the
// whole per-image path ONE C++ stage on the existing thread-pool
// pattern: libjpeg(-turbo) decode (with DCT scaled decoding — 1/2, 1/4,
// 1/8 — whenever the crop region stays >= the output size, which cuts
// IDCT work up to 64x on large sources), the classic ResNet
// RandomResizedCrop / eval central-crop in ORIGINAL image coordinates,
// fused bilinear resize straight from the scaled crop window into the
// normalized float32 output. Randomness is a splitmix64 stream seeded
// PER IMAGE by the caller (exact-resume capable: seed = f(stream
// position)); the numpy mirror in data/imagenet.py reproduces the same
// draws bit-for-bit so parity is testable without hardware.
//
// ABI (see tensorflow_examples_tpu/native/__init__.py):
//   fj_decode_augment_batch : concatenated jpeg bytes -> f32 NHWC batch
//   fj_jpeg_dims            : header-only (h, w) probe
//
// Build: make -C native build/libfastjpeg.so   (links -ljpeg; the lib
// is optional — the Python side falls back to the tf.data decode path
// when it is absent, same degradation contract as libfastdata.)

#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

// ------------------------------------------------------------- threading

template <typename Fn>
void parallel_for(int64_t n, int threads, Fn fn) {
  if (threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk, hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

// ------------------------------------------------------------------ rng
//
// splitmix64 — tiny, seedable, and trivially mirrored in Python ints
// (data/imagenet.py _SplitMix64). All uniforms are drawn as
// (x >> 11) * 2^-53 float64 so both sides agree bit-for-bit.

struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    s += 0x9E3779B97F4A7C15ULL;
    uint64_t z = s;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  double u01() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

// ------------------------------------------------------------ jpeg glue

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// Decoded window: `rgb` holds rows [oy0, oy0+h) x cols [ox0, ox0+w) of
// the 1/denom-scaled image (libjpeg may widen the column window to MCU
// boundaries, so ox0/w can cover more than requested).
struct Window {
  std::vector<uint8_t> rgb;
  int oy0 = 0, ox0 = 0, h = 0, w = 0;   // window placement, scaled coords
  int sh = 0, sw = 0;                   // full scaled image dims
};

// Decode only the scaled-coordinate window [wy0, wy0+wh) — the partial
// decode tf.image's decode_and_crop_jpeg uses, via libjpeg-turbo's
// jpeg_skip_scanlines / jpeg_crop_scanline — DCT-downscaled by
// 1/denom. Returns false on any libjpeg error (corrupt stream).
bool decode_window(const uint8_t* data, size_t len, int denom, int wy0,
                   int wh, int wx0, int ww, Window* win) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  cinfo.scale_num = 1;
  cinfo.scale_denom = static_cast<unsigned int>(denom);
  jpeg_start_decompress(&cinfo);
  const int sh = static_cast<int>(cinfo.output_height);
  const int sw = static_cast<int>(cinfo.output_width);
  // Clamp the request to the scaled frame.
  if (wy0 < 0) wy0 = 0;
  if (wx0 < 0) wx0 = 0;
  if (wy0 + wh > sh) wh = sh - wy0;
  if (wx0 + ww > sw) ww = sw - wx0;
  if (wh <= 0 || ww <= 0) {
    wy0 = wx0 = 0;
    wh = sh;
    ww = sw;
  }
  // Column crop first (may widen to an MCU boundary).
  JDIMENSION xoff = static_cast<JDIMENSION>(wx0);
  JDIMENSION xwidth = static_cast<JDIMENSION>(ww);
  if (!(xoff == 0 && xwidth == static_cast<JDIMENSION>(sw))) {
    jpeg_crop_scanline(&cinfo, &xoff, &xwidth);
  }
  if (wy0 > 0) {
    jpeg_skip_scanlines(&cinfo, static_cast<JDIMENSION>(wy0));
  }
  const int oy0 = static_cast<int>(cinfo.output_scanline);
  const int w = static_cast<int>(xwidth);
  win->rgb.resize(static_cast<size_t>(wh) * w * 3);
  int row = 0;
  while (row < wh && cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW dst = win->rgb.data() + static_cast<size_t>(row) * w * 3;
    row += static_cast<int>(jpeg_read_scanlines(&cinfo, &dst, 1));
  }
  // Rows below the window are never decoded: abort, don't finish.
  jpeg_abort_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  win->oy0 = oy0;
  win->ox0 = static_cast<int>(xoff);
  win->h = row;
  win->w = w;
  win->sh = sh;
  win->sw = sw;
  return row == wh;
}

// Header-only dimensions. Returns false on error.
bool jpeg_dims(const uint8_t* data, size_t len, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  *h = static_cast<int>(cinfo.image_height);
  *w = static_cast<int>(cinfo.image_width);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ---------------------------------------------------------- crop policy

struct Crop {
  int y0, x0, h, w;  // in ORIGINAL image coordinates
  bool flip;
};

// Draw order is the contract with the numpy mirror: per attempt
// (u_area, u_logratio), then on success (u_y, u_x); after the loop
// u_flip. Mirrors torchvision RandomResizedCrop semantics.
Crop train_crop(int H, int W, SplitMix64* rng) {
  const double log_lo = std::log(3.0 / 4.0), log_hi = std::log(4.0 / 3.0);
  Crop c{};
  bool found = false;
  for (int attempt = 0; attempt < 10 && !found; ++attempt) {
    double a_frac = 0.08 + rng->u01() * 0.92;
    double ratio = std::exp(log_lo + rng->u01() * (log_hi - log_lo));
    double area = a_frac * H * W;
    int w = static_cast<int>(std::floor(std::sqrt(area * ratio) + 0.5));
    int h = static_cast<int>(std::floor(std::sqrt(area / ratio) + 0.5));
    if (w >= 1 && h >= 1 && w <= W && h <= H) {
      c.y0 = static_cast<int>(std::floor(rng->u01() * (H - h + 1)));
      c.x0 = static_cast<int>(std::floor(rng->u01() * (W - w + 1)));
      c.h = h;
      c.w = w;
      found = true;
    }
  }
  if (!found) {  // fallback: central min-square (matches the mirror)
    int m = H < W ? H : W;
    c.h = c.w = m;
    c.y0 = (H - m) / 2;
    c.x0 = (W - m) / 2;
  }
  c.flip = rng->u01() < 0.5;
  return c;
}

Crop eval_crop(int H, int W) {
  int m = H < W ? H : W;
  int crop = static_cast<int>(0.875 * m);
  if (crop < 1) crop = 1;
  return Crop{(H - crop) / 2, (W - crop) / 2, crop, crop, false};
}

// Largest DCT denom in {8,4,2,1} that keeps the scaled crop >= out so
// the bilinear stage only ever downsamples.
int pick_denom(const Crop& c, int out) {
  for (int d : {8, 4, 2}) {
    if (c.h / d >= out && c.w / d >= out) return d;
  }
  return 1;
}

// Bilinear-sample the crop (original coords) from a decoded window of
// the 1/denom-scaled image, flip, normalize, write [out, out, 3]
// floats. Sample indices are computed in scaled-IMAGE coordinates
// (identical to the full-frame formulation, so the numpy mirror holds)
// and only then rebased into the window, whose one-pixel margin covers
// the bilinear neighbors; clamping against the window edge equals
// frame-edge clamping because the window is clamped to the frame.
void resize_normalize(const Window& win, int denom, const Crop& c, int out,
                      const float* mean, const float* inv_std, float* dst) {
  const double inv_d = 1.0 / denom;
  const int sh = win.sh, sw = win.sw;
  auto rebase_y = [&](int y) {
    y -= win.oy0;
    if (y < 0) y = 0;
    if (y >= win.h) y = win.h - 1;
    return y;
  };
  auto rebase_x = [&](int x) {
    x -= win.ox0;
    if (x < 0) x = 0;
    if (x >= win.w) x = win.w - 1;
    return x;
  };
  for (int oy = 0; oy < out; ++oy) {
    // Original-coordinate sample center (half-pixel convention), then
    // mapped into the scaled image's pixel grid.
    double sy = c.y0 + (oy + 0.5) * c.h / out - 0.5;
    double sys = (sy + 0.5) * inv_d - 0.5;
    int y1 = static_cast<int>(std::floor(sys));
    double fy = sys - y1;
    int y2 = y1 + 1;
    if (y1 < 0) y1 = 0;
    if (y2 < 0) y2 = 0;
    if (y1 >= sh) y1 = sh - 1;
    if (y2 >= sh) y2 = sh - 1;
    int by1 = rebase_y(y1), by2 = rebase_y(y2);
    for (int ox = 0; ox < out; ++ox) {
      int ox_dst = c.flip ? (out - 1 - ox) : ox;
      double sx = c.x0 + (ox + 0.5) * c.w / out - 0.5;
      double sxs = (sx + 0.5) * inv_d - 0.5;
      int x1 = static_cast<int>(std::floor(sxs));
      double fx = sxs - x1;
      int x2 = x1 + 1;
      if (x1 < 0) x1 = 0;
      if (x2 < 0) x2 = 0;
      if (x1 >= sw) x1 = sw - 1;
      if (x2 >= sw) x2 = sw - 1;
      int bx1 = rebase_x(x1), bx2 = rebase_x(x2);
      const uint8_t* base = win.rgb.data();
      const uint8_t* p11 = base + (static_cast<size_t>(by1) * win.w + bx1) * 3;
      const uint8_t* p12 = base + (static_cast<size_t>(by1) * win.w + bx2) * 3;
      const uint8_t* p21 = base + (static_cast<size_t>(by2) * win.w + bx1) * 3;
      const uint8_t* p22 = base + (static_cast<size_t>(by2) * win.w + bx2) * 3;
      float* q = dst + (static_cast<size_t>(oy) * out + ox_dst) * 3;
      for (int k = 0; k < 3; ++k) {
        double v = (1 - fy) * ((1 - fx) * p11[k] + fx * p12[k]) +
                   fy * ((1 - fx) * p21[k] + fx * p22[k]);
        q[k] = (static_cast<float>(v) * (1.0f / 255.0f) - mean[k]) *
               inv_std[k];
      }
    }
  }
}

}  // namespace

extern "C" {

// Returns the number of FAILED decodes (0 == all good). Failed images
// get ok_flags[i] = 0 and a zeroed output slot; callers decide whether
// to drop or substitute.
int64_t fj_decode_augment_batch(const uint8_t* data, const int64_t* offsets,
                                int64_t n, int32_t train, int32_t out_size,
                                const uint64_t* seeds, const float* mean,
                                const float* inv_std, float* out,
                                int64_t threads, uint8_t* ok_flags) {
  std::vector<int64_t> failures(n > 0 ? n : 1, 0);
  parallel_for(n, static_cast<int>(threads), [&](int64_t i) {
    const uint8_t* img = data + offsets[i];
    size_t len = static_cast<size_t>(offsets[i + 1] - offsets[i]);
    float* dst =
        out + static_cast<size_t>(i) * out_size * out_size * 3;
    int H = 0, W = 0;
    Crop c;
    if (!jpeg_dims(img, len, &H, &W) || H < 1 || W < 1) {
      std::memset(dst, 0, sizeof(float) * out_size * out_size * 3);
      ok_flags[i] = 0;
      failures[i] = 1;
      return;
    }
    if (train) {
      SplitMix64 rng(seeds[i]);
      c = train_crop(H, W, &rng);
    } else {
      c = eval_crop(H, W);
    }
    int denom = pick_denom(c, out_size);
    // Scaled-coordinate window covering the crop plus a one-pixel
    // bilinear margin; decode_window clamps it to the frame.
    int wy0 = c.y0 / denom - 1;
    int wh = (c.y0 + c.h + denom - 1) / denom - wy0 + 2;
    int wx0 = c.x0 / denom - 1;
    int ww = (c.x0 + c.w + denom - 1) / denom - wx0 + 2;
    Window win;
    if (!decode_window(img, len, denom, wy0, wh, wx0, ww, &win)) {
      std::memset(dst, 0, sizeof(float) * out_size * out_size * 3);
      ok_flags[i] = 0;
      failures[i] = 1;
      return;
    }
    resize_normalize(win, denom, c, out_size, mean, inv_std, dst);
    ok_flags[i] = 1;
  });
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += failures[i];
  return total;
}

int32_t fj_jpeg_dims(const uint8_t* data, int64_t len, int32_t* h,
                     int32_t* w) {
  int hh = 0, ww = 0;
  if (!jpeg_dims(data, static_cast<size_t>(len), &hh, &ww)) return 1;
  *h = hh;
  *w = ww;
  return 0;
}

}  // extern "C"
