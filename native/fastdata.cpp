// Threaded host-side input-pipeline kernels (ctypes ABI).
//
// TPU-native replacement for the role the reference's C++ tf.data runtime
// played (SURVEY.md §2c): the augmentation/normalization inner loops that
// sit on the host CPU between storage and the device transfer. Python
// (numpy) drives determinism — all randomness (crop offsets, flip flags)
// is decided by the caller's seeded Generator and passed in — while the
// byte-crunching runs here, multithreaded, without the GIL.
//
// Exposed C ABI (see tensorflow_examples_tpu/native/__init__.py):
//   crop_flip_normalize_u8 : uint8 NHWC batch -> cropped/flipped/
//                            normalized float32 batch
//   normalize_u8           : uint8 NHWC batch -> normalized float32 batch
//
// Build: make -C native (g++ -O3 -shared; no external dependencies).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) over a small thread pool.
void parallel_for(int64_t n, int threads, void (*fn)(int64_t, void*), void* ctx) {
  if (threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i, ctx);
    return;
  }
  std::vector<std::thread> pool;
  std::int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk, hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i) fn(i, ctx);
    });
  }
  for (auto& th : pool) th.join();
}

struct CropCtx {
  const uint8_t* in;
  float* out;
  const int32_t* ys;      // [b] crop row offsets (into padded coords)
  const int32_t* xs;      // [b] crop col offsets
  const uint8_t* flips;   // [b] horizontal-flip flags
  const float* mean;      // [c]
  const float* inv_std;   // [c]
  int64_t in_h, in_w, out_h, out_w, ch, pad;
};

// One example: reflect-pad by ctx.pad, crop out_h×out_w at (ys, xs),
// optional h-flip, then (x/255 - mean) * inv_std.
void crop_one(int64_t b, void* vctx) {
  const CropCtx& c = *static_cast<CropCtx*>(vctx);
  const uint8_t* src = c.in + b * c.in_h * c.in_w * c.ch;
  float* dst = c.out + b * c.out_h * c.out_w * c.ch;
  const bool flip = c.flips[b] != 0;
  for (int64_t oy = 0; oy < c.out_h; ++oy) {
    int64_t iy = oy + c.ys[b] - c.pad;  // padded coords -> source coords
    if (iy < 0) iy = -iy;               // reflect
    if (iy >= c.in_h) iy = 2 * c.in_h - 2 - iy;
    for (int64_t ox = 0; ox < c.out_w; ++ox) {
      int64_t ox_src = flip ? (c.out_w - 1 - ox) : ox;
      int64_t ix = ox_src + c.xs[b] - c.pad;
      if (ix < 0) ix = -ix;
      if (ix >= c.in_w) ix = 2 * c.in_w - 2 - ix;
      const uint8_t* px = src + (iy * c.in_w + ix) * c.ch;
      float* q = dst + (oy * c.out_w + ox) * c.ch;
      for (int64_t k = 0; k < c.ch; ++k) {
        q[k] = (px[k] * (1.0f / 255.0f) - c.mean[k]) * c.inv_std[k];
      }
    }
  }
}

struct NormCtx {
  const uint8_t* in;
  float* out;
  const float* mean;
  const float* inv_std;
  int64_t hw, ch;
};

void norm_one(int64_t b, void* vctx) {
  const NormCtx& c = *static_cast<NormCtx*>(vctx);
  const uint8_t* src = c.in + b * c.hw * c.ch;
  float* dst = c.out + b * c.hw * c.ch;
  for (int64_t i = 0; i < c.hw; ++i) {
    for (int64_t k = 0; k < c.ch; ++k) {
      dst[i * c.ch + k] =
          (src[i * c.ch + k] * (1.0f / 255.0f) - c.mean[k]) * c.inv_std[k];
    }
  }
}

}  // namespace

extern "C" {

void crop_flip_normalize_u8(const uint8_t* in, float* out, const int32_t* ys,
                            const int32_t* xs, const uint8_t* flips,
                            const float* mean, const float* inv_std,
                            int64_t batch, int64_t in_h, int64_t in_w,
                            int64_t out_h, int64_t out_w, int64_t ch,
                            int64_t pad, int64_t threads) {
  CropCtx ctx{in, out, ys, xs, flips, mean, inv_std,
              in_h, in_w, out_h, out_w, ch, pad};
  parallel_for(batch, static_cast<int>(threads), crop_one, &ctx);
}

void normalize_u8(const uint8_t* in, float* out, const float* mean,
                  const float* inv_std, int64_t batch, int64_t hw, int64_t ch,
                  int64_t threads) {
  NormCtx ctx{in, out, mean, inv_std, hw, ch};
  parallel_for(batch, static_cast<int>(threads), norm_one, &ctx);
}

}  // extern "C"
