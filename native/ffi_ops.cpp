// XLA custom-call ops in C++ via the XLA FFI (SURVEY.md §2c obligation:
// "the XLA custom-call C++ scaffold for any op Pallas can't express").
//
// On TPU the idiomatic kernel path is Pallas (ops/attention.py,
// ops/cross_entropy.py); XLA:TPU does not accept user custom-calls the
// way XLA:CPU/GPU do. This scaffold therefore targets the CPU backend —
// it is the framework's mechanism for host-side compiled ops and the
// template to extend if an op ever needs to escape both XLA fusion and
// Pallas. Registered op:
//
//   fused_cross_entropy_fwd : f32[n, v] logits, s32[n] labels
//                             -> (f32[n] nll, f32[n] lse)
//   (single pass, online logsumexp — the CPU analogue of the Pallas
//    kernel in ops/cross_entropy.py, shares its unit tests)
//
// Build: make -C native (uses jax.ffi.include_dir() headers, no jaxlib
// link dependency — the FFI is header-only).

#include <algorithm>
#include <cmath>
#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error FusedCrossEntropyFwd(
    ffi::Buffer<ffi::F32> logits, ffi::Buffer<ffi::S32> labels,
    ffi::ResultBuffer<ffi::F32> nll, ffi::ResultBuffer<ffi::F32> lse) {
  auto dims = logits.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("logits must be rank 2");
  }
  const int64_t n = dims[0], v = dims[1];
  const float* x = logits.typed_data();
  const int32_t* y = labels.typed_data();
  for (int64_t i = 0; i < n; ++i) {
    const float* row = x + i * v;
    // Online logsumexp: one pass, no [v] scratch.
    float m = -INFINITY, s = 0.0f;
    for (int64_t j = 0; j < v; ++j) {
      float z = row[j];
      if (z > m) {
        s = s * std::exp(m - z) + 1.0f;
        m = z;
      } else {
        s += std::exp(z - m);
      }
    }
    float l = m + std::log(s);
    lse->typed_data()[i] = l;
    int64_t label = std::min<int64_t>(std::max<int64_t>(y[i], 0), v - 1);
    nll->typed_data()[i] = l - row[label];
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    kFusedCrossEntropyFwd, FusedCrossEntropyFwd,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>());

extern "C" {
// Looked up via ctypes and handed to jax.ffi.register_ffi_target through
// a PyCapsule (tensorflow_examples_tpu/native/__init__.py).
void* fused_cross_entropy_fwd_handler() {
  return reinterpret_cast<void*>(kFusedCrossEntropyFwd);
}
}
