"""TPU-gated kernel tests — ambient backend, NO cpu pin.

Unlike ``tests/conftest.py`` (which pins the cpu backend and 8 fake
devices so everything runs hardware-free), this directory runs against
whatever backend jax resolves — the point is compiled-kernel numerics on
the real chip (VERDICT r2 item 6: all Pallas parity tests ran in
interpret mode on CPU; the compiled TPU kernels were exercised only by
benches, which never compare numerics).

COLLECTION GUARD: this rig's axon TPU plugin can hang *indefinitely* at
backend init, and the modules' skipif marks touch the backend at import
— so a bare ``pytest tests_tpu/`` would hang before any skip fires.
The conftest therefore probes the backend in a SUBPROCESS with a hard
timeout and, unless it reports exactly ``tpu`` (what the live tunnel
reports — the kernels' own ``interpret = default_backend() != "tpu"``
switches hinge on the same string, so any other value would run
interpret-mode anyway and prove nothing about compiled numerics), tells
pytest to ignore the test modules entirely, never importing them.
pytest then exits with "no tests collected" — bench.py's selftest
reports that as ok=False with the probe's reason, which is the honest
verdict for a selftest that could not touch the chip (the old
import-then-skip behavior reported ok=True with ZERO compiled
assertions run). When the chip is healthy the probe costs a few seconds
and everything runs compiled.

Kept deliberately self-contained (no import of bench.py — pytest does
not guarantee the repo root on sys.path for this conftest), but aligned
with bench.py's ``_probe_backend`` semantics and diagnostics.

Run: ``python -m pytest tests_tpu/ -q`` on a TPU host, or via
``python bench.py --bench=selftest`` (subprocess with a hard timeout).
"""

import subprocess
import sys

collect_ignore_glob: list = []


def _probe_backend(timeout_s: float = 120.0) -> tuple[str, str]:
    """(backend_name, detail). Popen + bounded post-kill wait: if the
    child is stuck uninterruptibly inside the TPU driver even SIGKILL
    doesn't reap it, and subprocess.run's own post-kill communicate()
    would block forever — the exact hang this guard exists to stop."""
    code = "import jax; print('BACKEND', jax.default_backend())"
    p = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        out, err = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        p.kill()
        try:
            _, err = p.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            err = "(child unkillable — stuck in driver)"
        return "hung", f"backend init exceeded {timeout_s:.0f}s; " + (
            (err or "").strip()[-300:]
        )
    for line in out.splitlines():
        if line.startswith("BACKEND "):
            return line.split()[1], ""
    return "error", (err or out).strip()[-300:]


import pytest


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    """Default 420 s SIGALRM timeout per test (no pytest-timeout in the
    image; wrapper hook, same mechanism as tests/conftest.py's). On
    2026-07-30 the compiled-kernel selftest wedged >900 s inside its
    FIRST tunnel compile and the whole live window was lost with no
    record of which test hung — a per-test alarm converts that into a
    named failure and lets the remaining tests try. Limitation shared
    with pytest-timeout's signal method: the alarm interrupts Python
    bytecode, not a C call that never re-enters the interpreter (the
    axon plugin's poll loop does re-enter, so in practice it fires)."""
    import signal

    seconds = 420
    # Scope to THIS directory's tests: conftest hooks register
    # session-wide, and in a combined `pytest tests/ tests_tpu/` run an
    # unconditional wrapper would fight tests/conftest.py's
    # marker-based alarm over the single process-wide SIGALRM.
    if "tests_tpu" not in str(getattr(item, "fspath", "")):
        return (yield)
    if not hasattr(signal, "SIGALRM"):
        return (yield)

    def on_alarm(signum, frame):
        raise TimeoutError(
            f"tests_tpu: {item.name} exceeded {seconds}s (wedged tunnel "
            f"compile? frame: {frame.f_code.co_filename}:{frame.f_lineno})"
        )

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


_backend, _detail = _probe_backend()
if _backend == "tpu":
    # Persistent compiled-executable cache: a tunnel wedge mid-session
    # means these tests get retried across live windows (see
    # tools/tpu_harvest.sh), and re-paying every kernel compile each
    # retry is what turned the 2026-07-30 18:10 window into zero
    # evidence. Importing jax here is safe (no backend init); the
    # config update must be in-process because sitecustomize already
    # imported jax, making env vars too late.
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_tests_tpu_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
if _backend != "tpu":
    sys.stderr.write(
        f"tests_tpu: ambient backend is {_backend!r}, not a live TPU — "
        "ignoring compiled-kernel test modules (they would hang or run "
        f"interpret-mode; see conftest docstring). {_detail}\n"
    )
    collect_ignore_glob = ["test_*.py"]
