"""TPU-gated kernel tests — ambient backend, NO cpu pin.

Unlike ``tests/conftest.py`` (which pins the cpu backend and 8 fake
devices so everything runs hardware-free), this directory runs against
whatever backend jax resolves — the point is compiled-kernel numerics on
the real chip (VERDICT r2 item 6: all Pallas parity tests ran in
interpret mode on CPU; the compiled TPU kernels were exercised only by
benches, which never compare numerics). Every module here skips itself
unless ``jax.default_backend() == "tpu"``.

Run: ``python -m pytest tests_tpu/ -q`` on a TPU host, or via
``python bench.py --bench=selftest`` (subprocess with a hard timeout —
this rig's TPU plugin can hang at init).
"""
