"""Compiled-kernel numerics on the live TPU (SURVEY.md §4).

The CPU suite proves the Pallas kernels in interpret mode; this module
proves the SAME kernels compiled by Mosaic on the real chip, at real
workload shapes, against the XLA reference implementations. Skipped
entirely off-TPU (the cpu-pinned suite under ``tests/`` owns that path).

Tolerances: inputs are bf16 (the production precision policy), softmax /
logsumexp accumulate in f32 in both the kernel and the reference, so
disagreement is bf16 rounding of inputs/outputs plus reordered f32
accumulation — a few ULP of bf16, hence the 2e-2 absolute bands below.
"""

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="compiled-kernel parity needs the TPU backend",
)

from tensorflow_examples_tpu.ops.attention import (  # noqa: E402
    attention_reference,
    flash_attention,
    flash_attention_with_lse,
)
from tensorflow_examples_tpu.ops.cross_entropy import (  # noqa: E402
    cross_entropy_per_example,
    cross_entropy_reference,
)


def _qkv(b, h, s, d, dtype=jnp.bfloat16, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in keys)


def _max_abs(a, b):
    return float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_fwd_compiled_parity(causal):
    # GPT-2 124M attention shape: 12 heads, seq 1024, head_dim 64.
    q, k, v = _qkv(2, 12, 1024, 64)
    out = flash_attention(q, k, v, causal=causal, interpret=False)
    ref = attention_reference(q, k, v, causal=causal)
    assert out.dtype == q.dtype
    assert _max_abs(out, ref) < 2e-2


def test_flash_bwd_compiled_parity():
    q, k, v = _qkv(2, 12, 1024, 64)
    g = jax.random.normal(jax.random.PRNGKey(7), q.shape, q.dtype)

    def loss(f):
        def inner(q, k, v):
            return jnp.sum(f(q, k, v).astype(jnp.float32) * g.astype(jnp.float32))

        return jax.grad(inner, argnums=(0, 1, 2))

    flash = lambda q, k, v: flash_attention(q, k, v, causal=True, interpret=False)
    ref = lambda q, k, v: attention_reference(q, k, v, causal=True)
    for got, want in zip(jax.jit(loss(flash))(q, k, v), jax.jit(loss(ref))(q, k, v)):
        # Gradients sum seq-many bf16 contributions; scale tolerance with
        # the reference's magnitude rather than assuming unit scale.
        band = 2e-2 * (1.0 + float(jnp.max(jnp.abs(want.astype(jnp.float32)))))
        assert _max_abs(got, want) < band


def test_flash_lse_compiled_parity():
    q, k, v = _qkv(1, 8, 2048, 64, seed=3)
    out, lse = flash_attention_with_lse(q, k, v, causal=True, interpret=False)
    ref = attention_reference(q, k, v, causal=True)
    # Reference lse computed directly (f32, causal-masked).
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (64**-0.5)
    row = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
    s = jnp.where(row >= col, s, -1e30)
    ref_lse = jax.nn.logsumexp(s, axis=-1)
    assert _max_abs(out, ref) < 2e-2
    assert _max_abs(lse, ref_lse) < 2e-2


def test_flash_key_bias_compiled_parity():
    # BERT padding-mask shape: non-causal, [batch, seq] key bias.
    q, k, v = _qkv(2, 12, 512, 64, seed=5)
    kb = jnp.where(
        jnp.arange(512)[None] < jnp.asarray([512, 300])[:, None], 0.0, -1e30
    ).astype(jnp.float32)
    out = flash_attention(
        q, k, v, causal=False, key_bias=kb, interpret=False
    )
    ref = attention_reference(q, k, v, causal=False, key_bias=kb)
    assert _max_abs(out, ref) < 2e-2


def test_flash_key_bias_bwd_compiled_parity():
    # The Mosaic rank-2 block constraint that broke the fwd bias spec
    # applied equally to both bwd kernels' kb specs; prove them compiled
    # too (interpret mode never enforces the constraint).
    q, k, v = _qkv(2, 12, 512, 64, seed=6)
    kb = jnp.where(
        jnp.arange(512)[None] < jnp.asarray([512, 300])[:, None], 0.0, -1e30
    ).astype(jnp.float32)
    g = jax.random.normal(jax.random.PRNGKey(8), q.shape, q.dtype)

    def grads(f):
        def inner(q, k, v):
            return jnp.sum(f(q, k, v).astype(jnp.float32) * g.astype(jnp.float32))

        return jax.jit(jax.grad(inner, argnums=(0, 1, 2)))

    flash = lambda q, k, v: flash_attention(
        q, k, v, causal=False, key_bias=kb, interpret=False
    )
    ref = lambda q, k, v: attention_reference(q, k, v, causal=False, key_bias=kb)
    for got, want in zip(grads(flash)(q, k, v), grads(ref)(q, k, v)):
        band = 2e-2 * (1.0 + float(jnp.max(jnp.abs(want.astype(jnp.float32)))))
        assert _max_abs(got, want) < band


def test_flash_decode_compiled_parity():
    from tensorflow_examples_tpu.ops.decode import (
        decode_attention_reference,
        flash_decode_attention,
    )

    # GPT-2 decode shape: 12 heads, 4k cache, single-token step + prefill.
    k = jax.random.normal(jax.random.PRNGKey(0), (2, 12, 4096, 64), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(1), (2, 12, 4096, 64), jnp.bfloat16)
    for q_len, length in ((1, 1000), (1, 4096), (512, 512), (256, 2048)):
        q = jax.random.normal(
            jax.random.PRNGKey(2), (2, 12, q_len, 64), jnp.bfloat16
        )
        out = flash_decode_attention(
            q, k, v, jnp.asarray(length), interpret=False
        )
        ref = decode_attention_reference(q, k, v, length)
        assert out.dtype == q.dtype
        assert _max_abs(out, ref) < 2e-2, (q_len, length)


def test_paged_decode_compiled_parity():
    """ISSUE 11: the fused paged-decode kernel (block-table gather +
    varlen masked attention in one launch) compiled on chip, fp and
    int8-dequant-in-kernel, against the XLA gather oracle."""
    import numpy as np

    from tensorflow_examples_tpu.core.precision import quantize_int8_rows
    from tensorflow_examples_tpu.ops.paged_decode import (
        paged_decode_attention,
        paged_decode_reference,
    )

    s, h, d, bs, nb_pool = 8, 12, 64, 16, 65
    rng = np.random.default_rng(0)
    q = jax.random.normal(jax.random.PRNGKey(0), (s, h, d), jnp.float32)
    kb = jax.random.normal(
        jax.random.PRNGKey(1), (nb_pool, h, bs, d), jnp.float32
    )
    vb = jax.random.normal(
        jax.random.PRNGKey(2), (nb_pool, h, bs, d), jnp.float32
    )
    nb = 8  # bucket = 128 rows
    perm = rng.permutation(np.arange(1, nb_pool))
    tables = jnp.asarray(
        perm[: s * nb].reshape(s, nb), jnp.int32
    )
    lengths = jnp.asarray(
        [1, 15, 16, 17, 64, 100, 127, 128], jnp.int32
    )
    out = paged_decode_attention(
        q, kb, vb, lengths, tables, interpret=False
    )
    ref = paged_decode_reference(q, kb, vb, lengths, tables)
    assert _max_abs(out, ref) < 2e-2
    qk, ks = quantize_int8_rows(kb)
    qv, vs = quantize_int8_rows(vb)
    out8 = paged_decode_attention(
        q, qk, qv, lengths, tables, k_scale=ks, v_scale=vs,
        interpret=False,
    )
    ref8 = paged_decode_reference(
        q, qk, qv, lengths, tables, k_scale=ks, v_scale=vs
    )
    assert _max_abs(out8, ref8) < 2e-2


def test_flash_decode_ladder_compiled_parity():
    """The power-of-two KV-grid bucket ladder (round 4) compiled on
    chip: one jit serves every context length through a 32k-slot cache,
    exact at and around bucket boundaries. Short contexts must also be
    FAST — the grid flatness itself is measured by bench.py
    --bench=decode_grid; this asserts the numerics."""
    from tensorflow_examples_tpu.ops.decode import (
        decode_attention_reference,
        flash_decode_attention,
    )

    k = jax.random.normal(jax.random.PRNGKey(4), (1, 8, 32768, 64), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(5), (1, 8, 32768, 64), jnp.bfloat16)
    q = jax.random.normal(jax.random.PRNGKey(6), (1, 8, 1, 64), jnp.bfloat16)
    f = jax.jit(lambda q_, k_, v_, n: flash_decode_attention(
        q_, k_, v_, n, interpret=False
    ))
    for length in (200, 256, 257, 4096, 4097, 32768):
        out = f(q, k, v, jnp.asarray(length))
        ref = decode_attention_reference(q, k, v, length)
        assert _max_abs(out, ref) < 2e-2, length


def test_fused_ce_compiled_parity():
    # GPT-2 LM-head shape: one step's tokens against the full 50257 vocab.
    n, v = 2048, 50257
    logits = jax.random.normal(jax.random.PRNGKey(0), (n, v), jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, v)
    nll = cross_entropy_per_example(logits, labels, interpret=False)
    ref = cross_entropy_reference(logits, labels)
    assert nll.dtype == jnp.float32
    assert _max_abs(nll, ref) < 2e-2


def test_fused_ce_bwd_compiled_parity():
    n, v = 1024, 50257
    logits = jax.random.normal(jax.random.PRNGKey(2), (n, v), jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(3), (n,), 0, v)

    def mean_nll(fn):
        return jax.jit(jax.grad(lambda lg: jnp.mean(fn(lg, labels))))

    got = mean_nll(
        lambda lg, lb: cross_entropy_per_example(lg, lb, interpret=False)
    )(logits)
    want = mean_nll(cross_entropy_reference)(logits)
    # dlogits entries are O(softmax/n) — tiny; absolute band scaled by n.
    assert _max_abs(got, want) < 2e-2 / n * 50


def test_flash_in_scan_compiled_parity():
    """The flash kernel INSIDE a lax.scan body, compiled by Mosaic on
    the chip — the steps_per_launch bundled-step composition. Proves a
    Pallas call under scan lowers/compiles on this backend and that
    per-slice outputs match per-launch calls, clearing the way for
    bundling flash-attention workload benches (the bundled bert/
    cifar10/mnist benches are XLA-attention; this is the flash case)."""
    qs, ks, vs = (
        jax.random.normal(
            jax.random.PRNGKey(i), (2, 1, 4, 256, 64), jnp.bfloat16
        )
        for i in range(3)
    )

    @jax.jit
    def scanned(qs, ks, vs):
        def body(carry, qkv):
            q, k, v = qkv
            o = flash_attention(q, k, v, causal=True, interpret=False)
            return carry + jnp.sum(o.astype(jnp.float32)), o

        return jax.lax.scan(body, jnp.float32(0.0), (qs, ks, vs))

    total, outs = scanned(qs, ks, vs)
    for i in range(2):
        ref = attention_reference(qs[i], ks[i], vs[i], causal=True)
        assert _max_abs(outs[i], ref) < 2e-2, i
    assert float(total) == pytest.approx(
        float(jnp.sum(outs.astype(jnp.float32))), rel=1e-3
    )


def test_moe_grouped_gmm_compiled_parity():
    """The sort-based grouped MoE path on the chip uses the MegaBlocks
    Pallas grouped matmul (``megablox.gmm``) instead of the generic
    masked ragged_dot the CPU tests exercise — so its compiled numerics
    (fwd AND grads) must be proven on silicon against the
    static-capacity scatter reference at a no-drop capacity."""
    from tensorflow_examples_tpu.parallel.moe import moe_ffn

    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    e, d, ff, b, s = 8, 256, 1024, 2, 512  # tile-divisible: gmm engages
    args = (
        jax.random.normal(ks[0], (d, e), jnp.float32) * 0.5,
        jax.random.normal(ks[1], (e, d, ff), jnp.float32) * 0.1,
        jax.random.normal(ks[2], (e, ff), jnp.float32) * 0.01,
        jax.random.normal(ks[3], (e, ff, d), jnp.float32) * 0.1,
        jax.random.normal(ks[4], (e, d), jnp.float32) * 0.01,
        jax.random.normal(ks[5], (b, s, d), jnp.float32),
    )
    kw = dict(capacity_factor=8.0, top_k=2, rng=None)
    want, aux_w, _ = jax.jit(
        lambda *a: moe_ffn(*a, impl="scatter", **kw)
    )(*args)
    got, aux_g, drop = jax.jit(
        lambda *a: moe_ffn(*a, impl="grouped", **kw)
    )(*args)
    assert float(drop) == 0.0
    assert _max_abs(got, want) < 5e-3
    assert float(aux_g) == pytest.approx(float(aux_w), rel=1e-4)

    def loss(impl):
        def f(*a):
            out, aux, _ = moe_ffn(*a, impl=impl, **kw)
            return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux

        return jax.jit(jax.grad(f, argnums=(0, 1, 3, 5)))

    for g_ref, g_new, name in zip(
        loss("scatter")(*args), loss("grouped")(*args),
        ("gate", "w_in", "w_out", "x"),
    ):
        band = 5e-3 * (1.0 + _max_abs(g_ref, jnp.zeros_like(g_ref)))
        assert _max_abs(g_new, g_ref) < band, name
