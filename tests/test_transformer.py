"""GPT-2 transformer: shapes, param count, decode cache, HF parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_examples_tpu.models import transformer


def tiny_cfg(**kw):
    base = dict(
        vocab_size=97,
        max_len=32,
        num_layers=2,
        num_heads=2,
        d_model=16,
        dropout=0.0,
        attention="xla",
    )
    base.update(kw)
    return transformer.TransformerConfig(**base)


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_cfg()
    model = transformer.Transformer(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)), jnp.int32
    )
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    return cfg, model, tokens, params


def test_logits_shape(tiny):
    cfg, model, tokens, params = tiny
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert jnp.isfinite(logits).all()


def test_param_count_gpt2_124m():
    """The real config must produce GPT-2 124M's canonical param count."""
    cfg = transformer.gpt2_124m()
    model = transformer.Transformer(cfg)
    shapes = jax.eval_shape(
        lambda r: model.init({"params": r}, jnp.zeros((1, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    )["params"]
    n = sum(np.prod(s.shape) for s in jax.tree.leaves(shapes))
    assert n == 124_439_808  # HF GPT2LMHeadModel (tied head), 124M


def test_flash_matches_xla(tiny):
    cfg, model, tokens, params = tiny
    ref = model.apply({"params": params}, tokens)
    flash_model = transformer.Transformer(tiny_cfg(attention="flash"))
    out = flash_model.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-4)


def test_causality(tiny):
    """Future tokens must not affect earlier logits."""
    cfg, model, tokens, params = tiny
    logits = model.apply({"params": params}, tokens)
    perturbed = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
    logits2 = model.apply({"params": params}, perturbed)
    np.testing.assert_allclose(
        np.asarray(logits[:, :-1]), np.asarray(logits2[:, :-1]), atol=1e-5
    )
    assert not np.allclose(np.asarray(logits[:, -1]), np.asarray(logits2[:, -1]))


def test_decode_cache_matches_full_forward(tiny):
    """Prefill + single-token decode steps == full non-decode forward."""
    cfg, model, tokens, params = tiny
    full = model.apply({"params": params}, tokens)

    cache = transformer.init_cache(model, batch_size=2)
    prefill_len = 20
    out1, vars_out = model.apply(
        {"params": params, "cache": cache},
        tokens[:, :prefill_len],
        decode=True,
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(full[:, :prefill_len]), np.asarray(out1), atol=2e-4
    )
    cache = vars_out["cache"]
    for t in range(prefill_len, tokens.shape[1]):
        step_logits, vars_out = model.apply(
            {"params": params, "cache": cache},
            tokens[:, t : t + 1],
            decode=True,
            mutable=["cache"],
        )
        cache = vars_out["cache"]
        np.testing.assert_allclose(
            np.asarray(full[:, t]), np.asarray(step_logits[:, 0]), atol=2e-4
        )


def test_flash_decode_path_matches_full_forward(tiny):
    """The default (flash-decode kernel) cache path must agree with the
    non-decode forward, same contract as the xla decode path above."""
    cfg, model, tokens, params = tiny
    full = model.apply({"params": params}, tokens)
    flash_model = transformer.Transformer(tiny_cfg(attention="flash"))

    cache = transformer.init_cache(flash_model, batch_size=2)
    out1, vars_out = flash_model.apply(
        {"params": params, "cache": cache},
        tokens[:, :20], decode=True, mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(full[:, :20]), np.asarray(out1), atol=2e-4
    )
    cache = vars_out["cache"]
    for t in range(20, 24):  # a few single-token steps through the kernel
        step_logits, vars_out = flash_model.apply(
            {"params": params, "cache": cache},
            tokens[:, t : t + 1], decode=True, mutable=["cache"],
        )
        cache = vars_out["cache"]
        np.testing.assert_allclose(
            np.asarray(full[:, t]), np.asarray(step_logits[:, 0]), atol=2e-4
        )


def test_generate_greedy_deterministic(tiny):
    cfg, model, tokens, params = tiny
    prompt = tokens[:, :4]
    out = transformer.generate(
        model, params, prompt,
        num_tokens=6, rng=jax.random.PRNGKey(1), temperature=0.0,
    )
    assert out.shape == (2, 10)
    out2 = transformer.generate(
        model, params, prompt,
        num_tokens=6, rng=jax.random.PRNGKey(2), temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # Greedy sampling must match argmax over the full forward pass.
    full = model.apply({"params": params}, out[:, :-1])
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(full[:, 3:], -1)), np.asarray(out[:, 4:])
    )


def test_hf_parity():
    """Our GPT-2 must match HF transformers' logits given imported weights.

    Random-init HF model (no network needed): exactness here certifies the
    whole architecture — layout, LN placement, gelu variant, tied head.
    """
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config, GPT2LMHeadModel

    from tensorflow_examples_tpu.models.hf_import import import_gpt2

    hf_cfg = GPT2Config(
        vocab_size=97, n_positions=32, n_embd=16, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    hf_model = GPT2LMHeadModel(hf_cfg).eval()
    cfg, params = import_gpt2(hf_model)
    assert cfg.num_layers == 2 and cfg.d_model == 16

    tokens = np.random.default_rng(0).integers(0, 97, (2, 16))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

    model = transformer.Transformer(
        transformer.TransformerConfig(
            vocab_size=97, max_len=32, num_layers=2, num_heads=2,
            d_model=16, dropout=0.0, attention="xla",
        )
    )
    params = jax.tree.map(jnp.asarray, params)
    ours = model.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=2e-4)
