"""End-to-end slice test: MNIST MLP on the shared loop (SURVEY.md §4
integration tier) — tiny synthetic config, asserts loss decreases and
checkpoints round-trip, on the 8-fake-device data-parallel mesh."""

import numpy as np

from tensorflow_examples_tpu.data.memory import eval_batches, train_iterator
from tensorflow_examples_tpu.data.sources import synthetic_images
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import mnist


def tiny_cfg(**kw):
    defaults = dict(
        device="cpu",
        global_batch_size=64,
        train_steps=60,
        log_every=20,
        learning_rate=1e-2,
        hidden=32,
        num_layers=1,
        dropout=0.0,
        precision="f32",
        checkpoint_every=50,
    )
    defaults.update(kw)
    return mnist.MnistConfig(**defaults)


def _data(n=512):
    return synthetic_images(n=n, shape=(28, 28, 1), num_classes=10, seed=0)


class TestMnistEndToEnd:
    def test_loss_decreases_dp8(self, devices):
        cfg = tiny_cfg()
        ds = _data()
        trainer = Trainer(mnist.make_task(cfg), cfg)
        it = train_iterator(ds, cfg.global_batch_size, seed=0)

        first = trainer._train_step(trainer.state, trainer._put_batch(next(it)))
        loss0 = float(first[1]["loss"])
        trainer.state = first[0]
        metrics = trainer.fit(it, num_steps=cfg.train_steps)
        assert metrics["loss"] < loss0 * 0.7, (loss0, metrics)

    def test_eval_weighted_padding(self, devices):
        cfg = tiny_cfg(train_steps=5)
        ds = _data(n=200)  # 200 % 64 != 0 → padded final batch
        trainer = Trainer(mnist.make_task(cfg), cfg)
        m = trainer.evaluate(eval_batches(ds, cfg.global_batch_size))
        assert 0.0 <= m["accuracy"] <= 1.0

    def test_checkpoint_roundtrip(self, devices, tmp_path):
        cfg = tiny_cfg(train_steps=10, checkpoint_every=5, workdir=str(tmp_path))
        ds = _data(n=128)
        trainer = Trainer(mnist.make_task(cfg), cfg)
        trainer.fit(train_iterator(ds, cfg.global_batch_size, seed=0))

        # Fresh trainer restores step 10 and params match.
        trainer2 = Trainer(mnist.make_task(cfg), cfg)
        from tensorflow_examples_tpu.train.checkpoint import CheckpointManager

        restored, step = CheckpointManager(str(tmp_path)).restore_latest(
            trainer2.state
        )
        assert step == 10
        for a, b in zip(
            np.ravel(
                np.concatenate(
                    [np.ravel(x) for x in _leaves(trainer.state.params)]
                )
            )[:5],
            np.ravel(
                np.concatenate([np.ravel(x) for x in _leaves(restored.params)])
            )[:5],
        ):
            assert a == b


    def test_resume_is_bit_exact(self, devices, tmp_path):
        """Interrupted+resumed run must equal the uninterrupted run exactly:
        same batches (iterator restarted at the restored step), same rng
        (folded from step), same params."""
        ds = _data(n=256)

        def data_fn(start):
            return train_iterator(ds, 64, seed=7, start_step=start)

        # Uninterrupted: 20 steps.
        cfg_a = tiny_cfg(train_steps=20, workdir=str(tmp_path / "a"),
                         checkpoint_every=100)
        tr_a = Trainer(mnist.make_task(cfg_a), cfg_a)
        tr_a.fit(data_fn)

        # Interrupted at 10, resumed to 20.
        wd = str(tmp_path / "b")
        cfg_b1 = tiny_cfg(train_steps=10, workdir=wd, checkpoint_every=100)
        Trainer(mnist.make_task(cfg_b1), cfg_b1).fit(data_fn)
        cfg_b2 = tiny_cfg(train_steps=20, workdir=wd, checkpoint_every=100)
        tr_b = Trainer(mnist.make_task(cfg_b2), cfg_b2)
        tr_b.fit(data_fn)

        for x, y in zip(_leaves(tr_a.state.params), _leaves(tr_b.state.params)):
            np.testing.assert_array_equal(x, y)


def _leaves(tree):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def test_zero1_matches_dp(mesh8):
    """ZeRO-1 sharded optimizer state must not change the math."""
    import jax

    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    def run(zero1):
        cfg = mnist.MnistConfig(
            global_batch_size=16, train_steps=5, hidden=64, num_layers=2,
            precision="f32", dropout=0.0, log_every=10**9,
            checkpoint_every=0, zero1=zero1, watchdog_secs=0,
        )
        trainer = Trainer(mnist.make_task(cfg), cfg, mesh=mesh8)
        ds = synthetic_images(n=256, shape=(28, 28, 1), num_classes=10, seed=0)
        it = train_iterator(ds, cfg.global_batch_size, seed=0)
        state, losses = trainer.state, []
        for _ in range(cfg.train_steps):
            state, m = trainer._train_step(state, trainer._put_batch(next(it)))
            losses.append(float(m["loss"]))
        return losses, state

    losses_dp, _ = run(zero1=False)
    losses_z1, state = run(zero1=True)
    np.testing.assert_allclose(losses_dp, losses_z1, rtol=1e-6)
    # Moments must actually be sharded over the data axis.
    mu = jax.tree.leaves(
        state.opt_state, is_leaf=lambda x: hasattr(x, "sharding")
    )
    specs = [x.sharding.spec for x in mu if hasattr(x, "ndim") and x.ndim >= 2]
    assert any("data" in str(s) for s in specs), specs
