"""graftlint — the repo's own static analysis suite (ISSUE 14).

Three layers of coverage:

* **Fixture goldens** — known-bad files under ``tests/lint_fixtures/``
  produce EXACTLY the pinned finding list per pass; known-good files
  (every documented exemption/idiom in one place) produce zero. The
  fixtures are the pass's contract: loosen a check and the bad pin
  fails, tighten it wrongly and the good pin fails.
* **Plumbing** — suppression-baseline round-trip (accepted counts,
  excess surfacing, stale-entry detection), the CLI's 0/1/2 exit-code
  contract, and bench_gate's baseline-growth WARN.
* **The tier-1 gate itself** — ``graftlint --all`` over the whole
  package with the committed baseline must exit 0: any new unguarded
  access, JAX hazard, or schema drift in the tree is a CI failure
  here, not a review comment. The runtime lock-order detector
  (armed per-test by conftest for the chaos/router/overload modules)
  gets its own unit pins: a cycle is recorded at
  ordering-establishment time with no deadlock needed.
"""

import json
import os
import sys
import threading
import time

import pytest

from tensorflow_examples_tpu.analysis import (
    common,
    drift,
    jaxhaz,
    lockorder,
    locks,
)

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
PACKAGE = os.path.join(REPO, "tensorflow_examples_tpu")
BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")

sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_gate  # noqa: E402
import graftlint  # noqa: E402


def _fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def _details(findings):
    return sorted((f.line, f.detail) for f in findings)


# ------------------------------------------------------ fixture goldens


class TestLockPassFixtures:
    def test_known_bad_exact_findings(self):
        got = _details(locks.run([_fixture("locks_bad.py")], REPO))
        assert got == [
            (22, "_free:read"),
            (25, "_free:write"),  # .append() mutates the container
            (28, "hits:write"),
            (37, "_DEPTH:write"),
        ]

    def test_known_good_is_clean(self):
        assert locks.run([_fixture("locks_good.py")], REPO) == []

    def test_finding_keys_are_line_number_free(self):
        (f, *_rest) = sorted(
            locks.run([_fixture("locks_bad.py")], REPO),
            key=lambda f: f.line,
        )
        assert str(f.line) not in f.key
        assert f.key.startswith("locks:")
        assert f.scope in f.key and f.detail in f.key


class TestJaxPassFixtures:
    def test_known_bad_exact_findings(self):
        got = _details(jaxhaz.run([_fixture("jax_bad.py")], REPO))
        assert got == [
            (11, "traced-branch:flag"),
            (13, "traced-sync:float()"),
            (29, "use-after-donate:kv"),
            (40, "use-after-donate:state"),
            (49, "host-sync:np.asarray"),
        ]

    def test_known_good_is_clean(self):
        # Pins the static-marker del, partial-bound buckets, None/
        # isinstance/len dispatch, donate-and-reassign-in-one-statement
        # (the engine's pool idiom), and host int() on the hot path.
        assert jaxhaz.run([_fixture("jax_good.py")], REPO) == []


class TestSchemaPassFixtures:
    def test_mini_tree_exact_findings(self):
        root = _fixture("schema_tree")
        got = sorted(
            f.detail
            for f in drift.run(
                [os.path.join(root, "tensorflow_examples_tpu")], root
            )
        )
        assert got == [
            "undocumented-counter:serving/undocumented_total",
            "undocumented-schema-key:ghost_key",
            "unknown-serving-key:rogue_key",
            "unstamped-schema-key:ghost_key",
        ]

    def test_tuples_learned_by_naming_convention(self):
        """ISSUE 15 satellite: the pass discovers every SERVING_KEYS*
        tuple in the real schema module by the naming convention — the
        v11 bump (and any future one) needs no pass-side list edit."""
        from tensorflow_examples_tpu.telemetry import schema

        src = drift._load(REPO, drift.SCHEMA_FILE)
        tuples = drift.schema_keys(src)
        assert "SERVING_KEYS_V12" in tuples
        assert tuples["SERVING_KEYS_V12"] == set(schema.SERVING_KEYS_V12)
        # Every live bump is discovered, none hand-listed.
        for n in range(6, 13):
            assert f"SERVING_KEYS_V{n}" in tuples
        # Precedence: the base (v4) tuple claims shared keys first.
        assert drift._tuple_order("SERVING_KEYS") < drift._tuple_order(
            "SERVING_KEYS_V6"
        )

    def test_instrument_prefixes_learned_from_schema_module(self):
        """The scanned namespaces come from INSTRUMENT_PREFIXES in the
        schema module (precision/ rides in via ISSUE 15); a schema file
        without the constant falls back to the pre-ISSUE-15 trio."""
        from tensorflow_examples_tpu.telemetry import schema

        src = drift._load(REPO, drift.SCHEMA_FILE)
        assert drift.instrument_prefixes(src) == tuple(
            schema.INSTRUMENT_PREFIXES
        )
        assert "precision/" in drift.instrument_prefixes(src)
        # The mini-tree fixture's schema module predates the constant.
        mini = drift._load(
            _fixture("schema_tree"),
            "tensorflow_examples_tpu/telemetry/schema.py",
        )
        assert drift.instrument_prefixes(mini) == (
            "serving/", "router/", "autoscaler/"
        )

    def test_precision_instruments_are_scanned(self):
        """The engine's precision/* gauges are inside the drift pass's
        net: scrubbing one from the docs would be a finding (proved by
        scanning the real engine file with the learned prefixes)."""
        src = common.load_source(
            os.path.join(
                REPO, "tensorflow_examples_tpu/serving/engine.py"
            ),
            REPO,
        )
        schema_src = drift._load(REPO, drift.SCHEMA_FILE)
        names = drift.registered_instruments(
            src, drift.instrument_prefixes(schema_src)
        )
        assert "precision/weight_bits" in names
        assert "precision/param_bytes" in names
        assert "serving/kv_pages_delta_skipped" in names


# ------------------------------------------------------------- baseline


class TestBaseline:
    def _findings(self, path="locks_bad.py"):
        return locks.run([_fixture(path)], REPO)

    def test_round_trip_suppresses_everything(self, tmp_path):
        findings = self._findings()
        bl_path = str(tmp_path / "bl.json")
        common.Baseline.from_findings(findings).save(bl_path)
        loaded = common.Baseline.load(bl_path)
        assert loaded.total() == len(findings)
        reported, suppressed, stale = common.apply_baseline(
            findings, loaded
        )
        assert reported == [] and stale == []
        assert len(suppressed) == len(findings)

    def test_excess_occurrences_surface_beyond_accepted_count(self):
        findings = self._findings()
        dup = findings[0]
        bl = common.Baseline({dup.key: 1})
        reported, suppressed, _ = common.apply_baseline(
            findings + [dup], bl
        )
        # one accepted occurrence suppressed; the duplicate reports
        assert dup.key in [f.key for f in reported]
        assert len(suppressed) == 1

    def test_removed_finding_reports_stale_entry(self, tmp_path):
        findings = self._findings()
        bl = common.Baseline.from_findings(findings)
        bl.counts["locks:gone/file.py:X.y:z:read"] = 1
        reported, _, stale = common.apply_baseline(findings, bl)
        assert reported == []
        assert stale == ["locks:gone/file.py:X.y:z:read"]

    def test_malformed_baseline_is_a_loud_error(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text('{"version": 99, "findings": {}}')
        with pytest.raises(ValueError, match="not a graftlint baseline"):
            common.Baseline.load(str(p))
        p.write_text('{"version": 1, "findings": {"k": -2}}')
        with pytest.raises(ValueError, match="positive"):
            common.Baseline.load(str(p))


# ------------------------------------------------------------------ CLI


class TestCLI:
    def test_clean_tree_exits_0(self):
        rc = graftlint.main(
            ["--pass", "locks", "--no-baseline", _fixture("locks_good.py")]
        )
        assert rc == 0

    def test_findings_exit_1(self, capsys):
        rc = graftlint.main(
            ["--pass", "locks", "--no-baseline", _fixture("locks_bad.py")]
        )
        assert rc == 1
        out = capsys.readouterr().out
        assert "[locks]" in out and "locks_bad.py:22" in out

    def test_missing_path_exits_2(self):
        assert graftlint.main(["--no-baseline", "/no/such/path.py"]) == 2

    def test_conflicting_flags_exit_2(self):
        assert graftlint.main(
            ["--all", "--pass", "locks", _fixture("locks_good.py")]
        ) == 2
        assert graftlint.main(
            ["--no-baseline", "--update-baseline",
             _fixture("locks_good.py")]
        ) == 2

    def test_update_baseline_then_clean_then_stale(self, tmp_path,
                                                   capsys):
        bl = str(tmp_path / "bl.json")
        mod = tmp_path / "mod.py"
        mod.write_text(open(_fixture("locks_bad.py")).read())
        root = ["--repo-root", str(tmp_path)]
        assert graftlint.main(
            ["--pass", "locks", "--baseline", bl,
             "--update-baseline", *root, str(mod)]
        ) == 0
        doc = json.loads(open(bl).read())
        assert doc["version"] == 1 and sum(
            doc["findings"].values()
        ) == 4
        # Same tree + committed baseline -> clean.
        assert graftlint.main(
            ["--pass", "locks", "--baseline", bl, *root, str(mod)]
        ) == 0
        # The SAME file no longer produces the accepted findings ->
        # the stale entries are named (exit stays 0: stale never
        # fails, it nudges the baseline to shrink toward the truth).
        mod.write_text(open(_fixture("locks_good.py")).read())
        capsys.readouterr()
        assert graftlint.main(
            ["--pass", "locks", "--baseline", bl, *root, str(mod)]
        ) == 0
        out = capsys.readouterr().out
        assert "[stale-baseline]" in out
        assert "remove the entry, or lower its count" in out


# ------------------------------------------- bench_gate baseline metric


class TestBenchGateLintBaseline:
    def _write(self, tmp_path, n):
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps(
            {"version": 1, "findings": {f"k{i}": 1 for i in range(n)}}
        ))
        return str(bl)

    def test_growth_warns(self, tmp_path, capsys):
        bl = self._write(tmp_path, 5)
        count = tmp_path / "bl.count"
        count.write_text("3\n")
        rc = bench_gate.report_lint_baseline(bl, str(count))
        out = capsys.readouterr().out
        assert rc == 0
        assert "[WARN]" in out and "GREW" in out and "5" in out

    def test_match_and_shrink_do_not_warn(self, tmp_path, capsys):
        bl = self._write(tmp_path, 3)
        count = tmp_path / "bl.count"
        count.write_text("3\n")
        assert bench_gate.report_lint_baseline(bl, str(count)) == 0
        assert "[WARN]" not in capsys.readouterr().out
        count.write_text("7\n")
        assert bench_gate.report_lint_baseline(bl, str(count)) == 0
        out = capsys.readouterr().out
        assert "[WARN]" not in out and "shrank" in out

    def test_committed_count_matches_committed_baseline(self):
        """The repo's own tracked count must equal the committed
        baseline total — growing one without the other is the exact
        drift the WARN exists to catch, so CI pins them equal."""
        total = bench_gate._lint_baseline_total(BASELINE)
        count_path = os.path.join(
            REPO, "tools", "graftlint_baseline.count"
        )
        with open(count_path) as f:
            tracked = int(f.read().strip())
        assert total == tracked, (
            f"tools/graftlint_baseline.json totals {total} but "
            f"graftlint_baseline.count says {tracked} — review the "
            "baseline change and update both together"
        )


# ------------------------------------------------- lock-order detector


class TestLockOrderDetector:
    def _pair(self, mon):
        a = lockorder._TrackedLock(mon, "lockA", reentrant=False)
        b = lockorder._TrackedLock(mon, "lockB", reentrant=False)
        return a, b

    def test_ab_ba_cycle_recorded_without_deadlock(self):
        """The classic hazard: thread 1 takes A then B, thread 2 takes
        B then A — SEQUENTIALLY, so no deadlock ever happens, but the
        ordering cycle must still be recorded the moment the second
        edge lands."""
        mon = lockorder.LockOrderMonitor()
        a, b = self._pair(mon)

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        th = threading.Thread(target=t1)
        th.start()
        th.join(5)
        assert mon.violations == []  # one order alone is fine
        th = threading.Thread(target=t2)
        th.start()
        th.join(5)
        assert len(mon.violations) == 1
        assert "lockA" in mon.violations[0]
        assert "lockB" in mon.violations[0]

    def test_consistent_order_is_clean(self):
        mon = lockorder.LockOrderMonitor()
        a, b = self._pair(mon)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert mon.violations == []

    def test_rlock_reentry_is_not_a_cycle(self):
        mon = lockorder.LockOrderMonitor()
        r = lockorder._TrackedLock(mon, "r", reentrant=True)
        with r:
            with r:  # re-entry by the owner: no self-edge
                pass
        assert mon.violations == []

    def test_three_lock_cycle_detected(self):
        mon = lockorder.LockOrderMonitor()
        a, b = self._pair(mon)
        c = lockorder._TrackedLock(mon, "lockC", reentrant=False)
        for first, second in ((a, b), (b, c), (c, a)):
            def run(x=first, y=second):
                with x:
                    with y:
                        pass
            th = threading.Thread(target=run)
            th.start()
            th.join(5)
        assert len(mon.violations) == 1  # closed on the c->a edge

    def test_arm_wraps_package_locks_only(self):
        mon = lockorder.arm()
        try:
            from tensorflow_examples_tpu.telemetry.registry import (
                MetricsRegistry,
            )

            reg = MetricsRegistry()  # allocates its lock in the package
            assert isinstance(reg._lock, lockorder._TrackedLock)
            raw = threading.Lock()  # allocated HERE (tests/): raw
            assert not isinstance(raw, lockorder._TrackedLock)
            with pytest.raises(RuntimeError, match="already armed"):
                lockorder.arm()
        finally:
            lockorder.disarm()
        assert threading.Lock is lockorder._real_lock
        # Locks created while armed keep working after disarm.
        reg.counter("x").inc()
        assert reg.counter("x").value == 1

    def test_nonblocking_acquire_failure_unwinds_held_stack(self):
        mon = lockorder.LockOrderMonitor()
        a, _ = self._pair(mon)
        assert a.acquire()
        got = []

        def contender():
            got.append(a.acquire(blocking=False))

        th = threading.Thread(target=contender)
        th.start()
        th.join(5)
        assert got == [False]
        a.release()
        # The failed acquire must not have left `a` on the contender
        # thread's held stack — a later acquisition from THIS thread
        # establishes no bogus edge and no violation.
        with a:
            pass
        assert mon.violations == []


# --------------------------------------------------- the tier-1 gate


class TestWholePackageGate:
    def test_graftlint_all_is_clean_with_committed_baseline(self,
                                                            capsys):
        """THE gate: every pass over the whole package, findings
        pinned to zero outside the committed suppression baseline.
        A new unguarded access to annotated state, a traced branch or
        host sync in jitted code, a use-after-donate, an undocumented
        counter, or a schema key stamped without a bump fails HERE."""
        rc = graftlint.run(
            [PACKAGE],
            list(graftlint.analysis.PASSES),
            repo_root=REPO,
            baseline_path=BASELINE,
        )
        out = capsys.readouterr().out
        assert rc == 0, f"graftlint found new issues:\n{out}"
        assert "0 finding(s)" in out

    def test_committed_baseline_has_no_stale_entries(self, capsys):
        """The baseline may only shrink toward the truth: an entry
        whose finding no longer occurs must be removed, not carried."""
        graftlint.run(
            [PACKAGE],
            list(graftlint.analysis.PASSES),
            repo_root=REPO,
            baseline_path=BASELINE,
        )
        out = capsys.readouterr().out
        assert "[stale-baseline]" not in out


# ----------------------------------------- review-fix regression pins


class TestReviewFixes:
    """Pins for the analysis-pass bugs caught in this PR's review:
    each test fails against the pre-fix implementation."""

    def _jax(self, src, tmp_path):
        p = tmp_path / "mod.py"
        p.write_text(src)
        return jaxhaz.run([str(p)], str(tmp_path))

    def test_double_donate_flags(self, tmp_path):
        """Passing an already-donated buffer to a SECOND donating call
        is the canonical deleted-Array bug — the donating-call-read
        exemption must only cover the call that performs the
        donation."""
        findings = self._jax(
            "import jax\n"
            "def _f(kv):\n"
            "    return kv\n"
            "F = jax.jit(_f, donate_argnums=(0,))\n"
            "def caller(kv):\n"
            "    out = F(kv)\n"
            "    out2 = F(kv)\n"
            "    return out, out2\n",
            tmp_path,
        )
        assert [f.detail for f in findings] == ["use-after-donate:kv"]
        assert findings[0].line == 7

    def test_donating_calls_own_args_do_not_flag(self, tmp_path):
        """Sibling args of the donating call evaluate before the
        donation: F(kv, n) must not flag n or kv at the call itself."""
        assert self._jax(
            "import jax\n"
            "def _f(kv, n):\n"
            "    return kv\n"
            "F = jax.jit(_f, donate_argnums=(0,))\n"
            "def caller(kv, n):\n"
            "    out = F(kv, n)\n"
            "    return out, n\n",
            tmp_path,
        ) == []

    def test_static_argnums_respected_in_assignment_form(self,
                                                         tmp_path):
        """`F = jax.jit(step, static_argnums=(1,))` — branching on the
        statically-marked parameter is host dispatch, not a traced
        branch (was a false positive: only static_argnames was read
        in the assignment form)."""
        assert self._jax(
            "import jax\n"
            "def step(x, use_cache):\n"
            "    if use_cache:\n"
            "        x = x + 1\n"
            "    return x\n"
            "F = jax.jit(step, static_argnums=(1,))\n",
            tmp_path,
        ) == []

    def test_nested_def_params_shadow_outer_traced_set(self, tmp_path):
        """A nested def's parameter shadows the outer traced name; its
        body is its own scope and must not be checked against the
        outer function's traced set (ast.walk does not prune)."""
        assert self._jax(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    def helper(x):\n"
            "        if x:\n"
            "            return 1\n"
            "        return 0\n"
            "    return x\n",
            tmp_path,
        ) == []

    def test_rlock_reentry_keeps_ordering_edges(self):
        """An inner RLock release must NOT erase the held-stack entry
        while the lock is still held — ordering edges established
        after a re-entry (r -> b here, then b -> r elsewhere) are
        exactly the cycles the detector exists for."""
        mon = lockorder.LockOrderMonitor()
        r = lockorder._TrackedLock(mon, "r", reentrant=True)
        b = lockorder._TrackedLock(mon, "b", reentrant=False)

        def t1():
            with r:
                with r:
                    pass
                with b:  # r is STILL held: r -> b must be recorded
                    pass

        def t2():
            with b:
                with r:
                    pass

        th = threading.Thread(target=t1)
        th.start()
        th.join(5)
        assert mon.edge_count() == 1  # the r -> b edge survived re-entry
        th = threading.Thread(target=t2)
        th.start()
        th.join(5)
        assert len(mon.violations) == 1

    def test_lockorder_monitor_pins_lock_ids(self):
        """The held-before graph is keyed by id(); CPython recycles a
        freed lock's id almost immediately, which aliased a NEW lock
        onto a dead lock's edges and manufactured cycles between locks
        that never coexisted. The monitor must pin every registered
        wrapper for the armed window so ids stay unique."""
        mon = lockorder.LockOrderMonitor()
        ids = set()
        for _ in range(50):
            a = lockorder._TrackedLock(mon, "a", reentrant=False)
            b = lockorder._TrackedLock(mon, "b", reentrant=False)
            with a:
                with b:
                    pass
            ids.add(id(a))
            ids.add(id(b))
            del a, b  # without the monitor's ref these ids recycle
        assert len(ids) == 100
        assert mon.violations == []

    def test_update_baseline_subset_preserves_out_of_scope(
        self, tmp_path, capsys
    ):
        """A targeted `--pass locks path/a.py --update-baseline` must
        MERGE into the baseline: accepted findings of other passes and
        other files are out of scope and must survive the rewrite
        (truncating them broke the next full `--all` gate run)."""
        bad = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.n = 0  # guard: self._lock\n"
            "    def bump(self):\n"
            "        self.n += 1\n"
        )
        a, b = tmp_path / "a.py", tmp_path / "b.py"
        a.write_text(bad)
        b.write_text(bad)
        bl = tmp_path / "baseline.json"
        common.Baseline({
            "locks:b.py:C.bump:n:write": 1,      # other file
            "jax:a.py:f:host-sync:item": 1,       # other pass, same file
        }).save(str(bl))
        rc = graftlint.run(
            [str(a)], ["locks"], repo_root=str(tmp_path),
            baseline_path=str(bl), update_baseline=True,
        )
        capsys.readouterr()
        assert rc == 0
        updated = common.Baseline.load(str(bl)).counts
        assert updated == {
            "locks:a.py:C.bump:n:write": 1,       # refreshed in scope
            "locks:b.py:C.bump:n:write": 1,       # preserved
            "jax:a.py:f:host-sync:item": 1,       # preserved
        }

    def test_container_mutations_are_writes(self, tmp_path):
        """`self._results[k] = v` and `self._free.append(x)` mutate the
        annotated container — classifying them 'read' (the Attribute's
        ctx is Load; the Store sits on the Subscript) gave the finding
        a wrong kind AND a wrong stable baseline key, inviting a
        genuine unguarded mutation to be triaged as an acceptable
        snapshot read."""
        p = tmp_path / "mod.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._results = {}  # guard: self._lock\n"
            "        self._free = []     # guard: self._lock\n"
            "    def put(self, k, v):\n"
            "        self._results[k] = v\n"
            "    def bump(self, k):\n"
            "        self._results[k] += 1\n"
            "    def push(self, x):\n"
            "        self._free.append(x)\n"
            "    def peek(self):\n"
            "        return self._results\n"
        )
        findings = locks.run([str(p)], str(tmp_path))
        assert _details(findings) == [
            (8, "_results:write"),
            (10, "_results:write"),
            (12, "_free:write"),
            (14, "_results:read"),
        ]

    def test_scoped_run_does_not_call_out_of_scope_entries_stale(
        self, tmp_path, capsys
    ):
        """`--pass locks some/dir` can say nothing about a jax entry in
        another file: printing it as '[stale-baseline] ... remove it'
        walks operators into deleting live suppressions (and breaking
        the next --all gate run)."""
        a = tmp_path / "a.py"
        a.write_text("x = 1\n")
        bl = tmp_path / "baseline.json"
        common.Baseline({"jax:b.py:f:host-sync:item": 1}).save(str(bl))
        rc = graftlint.run(
            [str(a)], ["locks"], repo_root=str(tmp_path),
            baseline_path=str(bl),
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "[stale-baseline]" not in out
        # The full-scope run still reports it stale.
        rc = graftlint.run(
            [str(tmp_path)], ["locks", "jax"],
            repo_root=str(tmp_path), baseline_path=str(bl),
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "[stale-baseline] jax:b.py:f:host-sync:item" in out

    def test_root_static_decl_survives_being_a_callee(self, tmp_path):
        """A jit root reached first as another root's callee (empty
        static set) must keep its OWN declared static_argnums — the
        intersection clobbered it and flagged a host-dispatch branch
        as a traced branch."""
        assert self._jax(
            "import jax\n"
            "def _a(x, use_cache):\n"
            "    if use_cache:\n"
            "        x = x + 1\n"
            "    return x\n"
            "def _b(x):\n"
            "    return _a(x, True)\n"
            "A = jax.jit(_a, static_argnums=(1,))\n"
            "B = jax.jit(_b)\n",
            tmp_path,
        ) == []

    def test_nested_def_param_is_not_use_after_donate(self, tmp_path):
        """A nested def's parameter shadows the donated outer name —
        its body is a fresh scope, exactly like the branch/sync checks
        (which prune nested defs via _walk_shallow)."""
        assert self._jax(
            "import jax\n"
            "def _f(kv):\n"
            "    return kv\n"
            "F = jax.jit(_f, donate_argnums=(0,))\n"
            "def caller(kv):\n"
            "    out = F(kv)\n"
            "    def helper(kv):\n"
            "        return kv + 1\n"
            "    return out, helper\n",
            tmp_path,
        ) == []

    def test_explicit_non_py_file_is_a_usage_error(self, tmp_path,
                                                   capsys):
        """iter_python_files drops non-.py files; an explicitly named
        one must exit 2, not report 'clean' over zero files."""
        script = tmp_path / "script"
        script.write_text("x = 1\n")
        assert graftlint.main(
            ["--all", "--no-baseline", str(script)]
        ) == 2
        capsys.readouterr()

    def test_tracked_lock_locked_matches_real_lock_surface(self):
        """The wrapper must not change the attribute surface relative
        to the real lock types — even hasattr/getattr probing must not
        differ only because the detector is armed (Py<3.14's C RLock
        has no locked())."""
        mon = lockorder.LockOrderMonitor()
        a = lockorder._TrackedLock(mon, "a", reentrant=False)
        assert a.locked() is False
        with a:
            assert a.locked() is True
        r = lockorder._TrackedLock(mon, "r", reentrant=True)
        assert hasattr(r, "locked") == hasattr(
            lockorder._real_rlock(), "locked"
        )
        if hasattr(r, "locked"):
            assert r.locked() is False  # Py >= 3.14: parity

    def test_rlock_depth_decrement_precedes_inner_release(self):
        """The re-entry depth must move while ownership is still
        exclusive — decrementing AFTER the inner release races the
        next owner's increment (lost update = stranded held-stack
        entry = false held-before edges in unrelated tests)."""
        mon = lockorder.LockOrderMonitor()
        r = lockorder._TrackedLock(mon, "r", reentrant=True)
        depths_at_inner_release = []

        class Stub:
            def acquire(self, blocking=True, timeout=-1):
                return True

            def release(self):
                depths_at_inner_release.append(r._depth)

        r._inner = Stub()
        r.acquire()
        r.acquire()
        r.release()
        r.release()
        assert depths_at_inner_release == [1, 0]

    def test_annassign_donate_and_reassign_is_clean(self, tmp_path):
        """`kv: Array = F(kv)` donates and reassigns in ONE statement,
        exactly like the plain-Assign idiom — AnnAssign was missing
        from the statement-ancestor tuple, so the target was never
        exempted."""
        assert self._jax(
            "import jax\n"
            "def _f(kv):\n"
            "    return kv\n"
            "F = jax.jit(_f, donate_argnums=(0,))\n"
            "def caller(kv):\n"
            "    kv: object = F(kv)\n"
            "    return kv\n",
            tmp_path,
        ) == []

    def test_drift_empty_request_set_reports_nothing(self, tmp_path):
        """A path set with zero .py files can say nothing — the old
        `or not requested` fallback flipped to whole-repo reporting,
        emitting findings the CLI's scoped baseline then refused to
        suppress."""
        tree = os.path.join(FIXTURES, "schema_tree")
        empty = tmp_path / "emptydir"
        empty.mkdir()
        assert drift.run([str(empty)], tree) == []
        # Sanity: the same tree WITH its files requested still finds.
        assert drift.run([tree], tree) != []

    def test_with_lock_call_style_matches_guard(self, tmp_path):
        """`with self._lock():` (a lock-returning accessor) matches a
        `# guard: self._lock` annotation — the comment documented the
        strip but the code never performed it."""
        p = tmp_path / "mod.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._big = threading.Lock()\n"
            "        self.hits = 0  # guard: self._big\n"
            "    def _big_(self):\n"
            "        return self._big\n"
            "    def bump(self):\n"
            "        with self._big():\n"
            "            self.hits += 1\n"
        )
        assert locks.run([str(p)], str(tmp_path)) == []

    def test_read_in_reassigning_statement_is_flagged(self, tmp_path):
        """`kv = kv + 1` after a donation reads the deleted array (the
        RHS evaluates before the rebind) — clearing the dead name at
        statement START masked exactly this crash."""
        findings = self._jax(
            "import jax\n"
            "def _f(kv):\n"
            "    return kv\n"
            "F = jax.jit(_f, donate_argnums=(0,))\n"
            "def caller(kv):\n"
            "    out = F(kv)\n"
            "    kv = kv + 1\n"
            "    return out, kv\n",
            tmp_path,
        )
        assert [f.detail for f in findings] == ["use-after-donate:kv"]
        assert findings[0].line == 7

    def test_cross_thread_release_pops_acquirer_stack(self):
        """threading.Lock may legally be released by a different
        thread (hand-off style); a thread-local held stack stranded
        the acquirer's entry forever, so every later acquire by that
        thread recorded a phantom held-before edge."""
        mon = lockorder.LockOrderMonitor()
        lk = lockorder._TrackedLock(mon, "L", reentrant=False)
        x = lockorder._TrackedLock(mon, "X", reentrant=False)
        lk.acquire()  # this thread acquires...
        th = threading.Thread(target=lk.release)  # ...another releases
        th.start()
        th.join(5)
        with x:
            pass  # L must NOT be considered held here: no L -> X edge
        assert mon.edge_count() == 0

        def other():
            with x:
                with lk:
                    pass

        th = threading.Thread(target=other)
        th.start()
        th.join(5)
        assert mon.violations == []

    def test_donate_argnames_registers_donor(self, tmp_path):
        """`donate_argnames=("kv",)` donates exactly like its argnums
        spelling — parsing it with the int-tuple helper yielded () and
        silently skipped the use-after-donate check entirely."""
        findings = self._jax(
            "import jax\n"
            "def _f(params, kv):\n"
            "    return kv\n"
            'F = jax.jit(_f, donate_argnames=("kv",))\n'
            "def caller(params, kv):\n"
            "    out = F(params, kv)\n"
            "    return out, kv\n",
            tmp_path,
        )
        assert [f.detail for f in findings] == ["use-after-donate:kv"]

    def test_ownership_recorded_at_success_not_attempt(self):
        """A blocked waiter must not clobber the holder's ownership:
        a cross-thread release would then pop the WAITER's stack and
        strand the holder's entry into phantom held-before edges."""
        mon = lockorder.LockOrderMonitor()
        lk = lockorder._TrackedLock(mon, "L", reentrant=False)
        x = lockorder._TrackedLock(mon, "X", reentrant=False)
        lk.acquire()  # this thread holds L
        attempting = threading.Event()

        def waiter():
            attempting.set()
            lk.acquire()  # blocks — must NOT take ownership yet
            lk.release()

        th = threading.Thread(target=waiter)
        th.start()
        assert attempting.wait(5)
        time.sleep(0.2)  # let the waiter block inside the inner acquire
        rel = threading.Thread(target=lk.release)  # cross-thread release
        rel.start()
        rel.join(5)
        th.join(5)
        with x:
            pass  # this thread's L entry was popped: no phantom edge
        assert mon.edge_count() == 0
        assert mon.violations == []

    def test_nested_def_under_with_is_not_guarded(self, tmp_path):
        """A callback defined under `with self._lock:` runs LATER,
        without the lock — the enclosing-with walk must stop at the
        def boundary instead of crediting the outer block. An inline
        lambda (sort key) executes synchronously under the block and
        stays clean."""
        p = tmp_path / "mod.py"
        p.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.hits = 0  # guard: self._lock\n"
            "    def sched(self, register):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                self.hits += 1\n"
            "            register(cb)\n"
            "    def bump(self, items):\n"
            "        with self._lock:\n"
            "            return sorted(items, key=lambda k: self.hits)\n"
        )
        findings = locks.run([str(p)], str(tmp_path))
        assert _details(findings) == [(9, "hits:write")]

    def test_decorated_donating_def_registers_donor(self, tmp_path):
        """An @partial(jax.jit, donate_argnums=...)-decorated def is
        called by its own name — it donates exactly like an assigned
        jitted callable, but donors were only ever collected from
        Assign statements."""
        findings = self._jax(
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def step(kv):\n"
            "    return kv\n"
            "def caller(kv):\n"
            "    out = step(kv)\n"
            "    return out, kv\n",
            tmp_path,
        )
        assert [f.detail for f in findings] == ["use-after-donate:kv"]

    def test_common_word_schema_key_requires_backticked_doc(self):
        """Schema keys that are ordinary words ('slots') appear all
        over the docs prose — only the backticked catalog form counts
        as documentation, or the drift check can never fire."""
        tree = os.path.join(FIXTURES, "schema_tree")
        src = common.load_source(
            os.path.join(
                tree, "tensorflow_examples_tpu", "telemetry",
                "schema.py"
            ),
            tree,
        )
        keys = drift.schema_keys(src)
        assert keys, "fixture schema must declare keys"
        docs = open(os.path.join(tree, "docs", "serving.md")).read()
        # the fixture documents its known-good keys backticked
        assert any(f"`{k}`" in docs for ks in keys.values() for k in ks)

    def test_release_bookkeeping_precedes_inner_release(self):
        """note_release must run while ownership is still exclusive —
        after the inner release, the next owner's note_acquired races
        it and the unconditional owners.pop erases the NEW holder's
        ownership record."""
        mon = lockorder.LockOrderMonitor()
        lk = lockorder._TrackedLock(mon, "L", reentrant=False)
        owners_at_inner_release = []

        class Stub:
            def acquire(self, blocking=True, timeout=-1):
                return True

            def release(self):
                with mon._mu:
                    owners_at_inner_release.append(dict(mon._owners))

        lk._inner = Stub()
        lk.acquire()
        lk.release()
        assert owners_at_inner_release == [{}]

    def test_hot_path_marker_found_above_decorators(self, tmp_path):
        """The marker block sits above the whole decorated function —
        the scan must not stop at the decorator line and silently
        exempt decorated hot paths."""
        findings = self._jax(
            "import numpy as np\n"
            "def deco(f):\n"
            "    return f\n"
            "# graftlint: hot-path\n"
            "@deco\n"
            "def decode(batch):\n"
            "    return np.asarray(batch)\n",
            tmp_path,
        )
        assert [f.detail for f in findings] == ["host-sync:np.asarray"]
