"""Native C++ host libraries: parity with numpy/XLA references.

The toolchain is part of the image (g++), so these do NOT skip silently —
a build failure should fail CI, not hide.
"""

import os

import numpy as np
import pytest

from tensorflow_examples_tpu import native


def test_fastdata_builds():
    assert native.available("fastdata"), "native/fastdata failed to build/load"


def test_crop_flip_normalize_parity():
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (16, 32, 32, 3), np.uint8)
    ys = rng.integers(0, 9, 16)
    xs = rng.integers(0, 9, 16)
    flips = rng.integers(0, 2, 16)
    mean = np.array([0.49, 0.48, 0.45], np.float32)
    std = np.array([0.25, 0.24, 0.26], np.float32)
    out = native.crop_flip_normalize(imgs, ys, xs, flips, mean, std, pad=4)
    assert out is not None and out.shape == (16, 32, 32, 3)

    ref = np.pad(
        imgs.astype(np.float32) / 255.0,
        ((0, 0), (4, 4), (4, 4), (0, 0)),
        mode="reflect",
    )
    ref = np.stack(
        [ref[i, ys[i] : ys[i] + 32, xs[i] : xs[i] + 32] for i in range(16)]
    )
    fl = flips.astype(bool)
    ref[fl] = ref[fl, :, ::-1]
    ref = (ref - mean) / std
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_normalize_parity():
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 255, (8, 17, 23, 3), np.uint8)  # odd dims
    mean = np.array([0.5, 0.4, 0.3], np.float32)
    std = np.array([0.2, 0.3, 0.25], np.float32)
    out = native.normalize(imgs, mean, std)
    assert out is not None
    ref = (imgs.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_normalize_single_thread_matches_multi():
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 255, (32, 8, 8, 3), np.uint8)
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)
    a = native.normalize(imgs, mean, std, threads=1)
    b = native.normalize(imgs, mean, std, threads=8)
    np.testing.assert_array_equal(a, b)


def test_ffi_cross_entropy_matches_reference():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from tensorflow_examples_tpu.ops.cross_entropy import cross_entropy_reference

    if not native.register_ffi_targets():
        pytest.fail("native/ffi_ops failed to build/register")
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(0, 3, (64, 101)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 101, 64), jnp.int32)
    nll, lse = native.ffi_cross_entropy(logits, labels)
    ref = cross_entropy_reference(logits, labels)
    np.testing.assert_allclose(np.asarray(nll), np.asarray(ref), atol=1e-5)
    # And it must compose under jit.
    jit_nll, _ = jax.jit(native.ffi_cross_entropy)(logits, labels)
    np.testing.assert_allclose(np.asarray(jit_nll), np.asarray(ref), atol=1e-5)


def test_cifar_augment_u8_matches_fallback():
    """Native fused CIFAR augment == numpy fallback, same rng."""
    from tensorflow_examples_tpu.data import augment

    rng_img = np.random.default_rng(5)
    batch = {
        "image": rng_img.integers(0, 255, (8, 32, 32, 3), np.uint8),
        "label": rng_img.integers(0, 10, 8, dtype=np.int32),
    }
    out_native = augment.cifar_augment(dict(batch), np.random.default_rng(9))

    # Force the numpy fallback by hiding the library.
    import tensorflow_examples_tpu.native as native_mod

    orig = native_mod.crop_flip_normalize
    native_mod.crop_flip_normalize = lambda *a, **k: None
    try:
        out_np = augment.cifar_augment(dict(batch), np.random.default_rng(9))
    finally:
        native_mod.crop_flip_normalize = orig
    assert out_native["image"].dtype == np.float32
    np.testing.assert_allclose(
        out_native["image"], out_np["image"], atol=1e-5
    )


# ------------------------------------------------------------- fastjpeg


def _make_jpeg(h, w, seed=0, quality=92):
    import io

    from PIL import Image

    rng = np.random.default_rng(seed)
    # Smooth low-frequency content: JPEG is near-lossless on it, so
    # decoder-rounding differences between libjpeg builds stay tiny.
    yy = np.linspace(0, np.pi * 2, h)[:, None]
    xx = np.linspace(0, np.pi * 3, w)[None, :]
    img = np.stack(
        [
            127 + 90 * np.sin(yy + p) * np.cos(xx + p)
            for p in rng.uniform(0, 3, 3)
        ],
        axis=-1,
    ).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


# libfastjpeg is the one OPTIONAL native lib (needs libjpeg headers;
# the Makefile's `all` treats it best-effort and imagenet.py falls back
# to the tf decode path). On hosts WITH the headers a build failure
# must still fail loudly, so only the header-less case skips.
_has_jpeg_headers = os.path.exists("/usr/include/jpeglib.h")
requires_fastjpeg = pytest.mark.skipif(
    not _has_jpeg_headers and not native.available("fastjpeg"),
    reason="libjpeg headers absent; fastjpeg is optional",
)


@requires_fastjpeg
def test_fastjpeg_builds():
    assert native.available("fastjpeg"), "native/fastjpeg failed to build/load"


@requires_fastjpeg
def test_jpeg_dims():
    assert native.jpeg_dims(_make_jpeg(48, 80)) == (48, 80)
    assert native.jpeg_dims(b"not a jpeg") is None


@requires_fastjpeg
@pytest.mark.parametrize("train", [True, False])
def test_decode_augment_matches_numpy_mirror(train):
    """The one-stage C++ decode+crop+resize+flip+normalize against the
    documented numpy mirror (same splitmix64 draws). Tolerance covers
    libjpeg-build IDCT rounding (PIL bundles its own libjpeg).
    out_size 48 keeps every crop < 2x the output, i.e. the denom=1
    decode path the mirror models exactly."""
    from tensorflow_examples_tpu.data import imagenet

    jpegs = [_make_jpeg(64 + 8 * i, 96 - 8 * i, seed=i) for i in range(6)]
    seeds = np.arange(100, 106, dtype=np.uint64)
    res = native.decode_augment_batch(
        jpegs,
        train=train,
        out_size=48,
        seeds=seeds,
        mean=imagenet.MEAN_RGB,
        std=imagenet.STDDEV_RGB,
    )
    assert res is not None
    out, ok = res
    assert out.shape == (6, 48, 48, 3) and ok.all()
    for i, j in enumerate(jpegs):
        ref = imagenet.decode_augment_reference(
            j, train=train, seed=int(seeds[i]), out_size=48
        )
        # ~2 uint8 counts of decoder slack, in normalized units.
        np.testing.assert_allclose(
            out[i], ref, atol=2.5 / 255.0 / 0.22,
            err_msg=f"image {i} (train={train})",
        )


@requires_fastjpeg
def test_decode_dct_scaled_path_close_to_full_decode():
    """A large source with a small output triggers the 1/denom DCT
    decode (the perf point of fastjpeg); the result must stay CLOSE to
    the full-decode mirror — scaled IDCT is a box-ish prefilter, not a
    different image."""
    from tensorflow_examples_tpu.data import imagenet

    jpeg = _make_jpeg(256, 320, seed=9)
    out, ok = native.decode_augment_batch(
        [jpeg],
        train=False,
        out_size=32,  # crop 224 -> denom 4
        seeds=None,
        mean=imagenet.MEAN_RGB,
        std=imagenet.STDDEV_RGB,
    )
    assert ok.all()
    ref = imagenet.decode_augment_reference(
        jpeg, train=False, seed=0, out_size=32
    )
    assert float(np.abs(out[0] - ref).mean()) < 0.08
    np.testing.assert_allclose(out[0], ref, atol=0.5)


@requires_fastjpeg
def test_decode_augment_failed_decode_flags():
    from tensorflow_examples_tpu.data import imagenet

    jpegs = [_make_jpeg(40, 40), b"garbage bytes", _make_jpeg(40, 40, seed=2)]
    out, ok = native.decode_augment_batch(
        jpegs,
        train=False,
        out_size=16,
        seeds=None,
        mean=imagenet.MEAN_RGB,
        std=imagenet.STDDEV_RGB,
    )
    assert list(ok) == [1, 0, 1]
    assert np.all(out[1] == 0)
    assert np.any(out[0] != 0) and np.any(out[2] != 0)


@requires_fastjpeg
def test_native_stream_feeds_training_batches(tmp_path):
    """End-to-end: TFRecord shards → native C++ decode stream →
    normalized batches with correct shapes/labels."""
    from tensorflow_examples_tpu.data import imagenet

    if not imagenet._native_decode_enabled():
        pytest.skip("fastjpeg unavailable")
    tf = imagenet._tf()
    path = str(tmp_path / "train-00000-of-00001")
    with tf.io.TFRecordWriter(path) as w:
        for i in range(8):
            ex = tf.train.Example(
                features=tf.train.Features(
                    feature={
                        "image/encoded": tf.train.Feature(
                            bytes_list=tf.train.BytesList(
                                value=[_make_jpeg(50 + i, 60, seed=i)]
                            )
                        ),
                        "image/class/label": tf.train.Feature(
                            int64_list=tf.train.Int64List(value=[i + 1])
                        ),
                    }
                )
            ).SerializeToString()
            w.write(ex)
    it = imagenet.tfrecord_iter(
        str(tmp_path), "train", 4, train=True, image_size=24, seed=0
    )
    b = next(it)
    assert b["image"].shape == (4, 24, 24, 3)
    assert b["image"].dtype == np.float32
    assert set(b["label"]) <= set(range(8))
    # normalized data: roughly centered, not raw uint8 scale
    assert abs(float(b["image"].mean())) < 3.0
