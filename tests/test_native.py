"""Native C++ host libraries: parity with numpy/XLA references.

The toolchain is part of the image (g++), so these do NOT skip silently —
a build failure should fail CI, not hide.
"""

import numpy as np
import pytest

from tensorflow_examples_tpu import native


def test_fastdata_builds():
    assert native.available("fastdata"), "native/fastdata failed to build/load"


def test_crop_flip_normalize_parity():
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (16, 32, 32, 3), np.uint8)
    ys = rng.integers(0, 9, 16)
    xs = rng.integers(0, 9, 16)
    flips = rng.integers(0, 2, 16)
    mean = np.array([0.49, 0.48, 0.45], np.float32)
    std = np.array([0.25, 0.24, 0.26], np.float32)
    out = native.crop_flip_normalize(imgs, ys, xs, flips, mean, std, pad=4)
    assert out is not None and out.shape == (16, 32, 32, 3)

    ref = np.pad(
        imgs.astype(np.float32) / 255.0,
        ((0, 0), (4, 4), (4, 4), (0, 0)),
        mode="reflect",
    )
    ref = np.stack(
        [ref[i, ys[i] : ys[i] + 32, xs[i] : xs[i] + 32] for i in range(16)]
    )
    fl = flips.astype(bool)
    ref[fl] = ref[fl, :, ::-1]
    ref = (ref - mean) / std
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_normalize_parity():
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 255, (8, 17, 23, 3), np.uint8)  # odd dims
    mean = np.array([0.5, 0.4, 0.3], np.float32)
    std = np.array([0.2, 0.3, 0.25], np.float32)
    out = native.normalize(imgs, mean, std)
    assert out is not None
    ref = (imgs.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_normalize_single_thread_matches_multi():
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 255, (32, 8, 8, 3), np.uint8)
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)
    a = native.normalize(imgs, mean, std, threads=1)
    b = native.normalize(imgs, mean, std, threads=8)
    np.testing.assert_array_equal(a, b)


def test_ffi_cross_entropy_matches_reference():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from tensorflow_examples_tpu.ops.cross_entropy import cross_entropy_reference

    if not native.register_ffi_targets():
        pytest.fail("native/ffi_ops failed to build/register")
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(0, 3, (64, 101)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 101, 64), jnp.int32)
    nll, lse = native.ffi_cross_entropy(logits, labels)
    ref = cross_entropy_reference(logits, labels)
    np.testing.assert_allclose(np.asarray(nll), np.asarray(ref), atol=1e-5)
    # And it must compose under jit.
    jit_nll, _ = jax.jit(native.ffi_cross_entropy)(logits, labels)
    np.testing.assert_allclose(np.asarray(jit_nll), np.asarray(ref), atol=1e-5)


def test_cifar_augment_u8_matches_fallback():
    """Native fused CIFAR augment == numpy fallback, same rng."""
    from tensorflow_examples_tpu.data import augment

    rng_img = np.random.default_rng(5)
    batch = {
        "image": rng_img.integers(0, 255, (8, 32, 32, 3), np.uint8),
        "label": rng_img.integers(0, 10, 8, dtype=np.int32),
    }
    out_native = augment.cifar_augment(dict(batch), np.random.default_rng(9))

    # Force the numpy fallback by hiding the library.
    import tensorflow_examples_tpu.native as native_mod

    orig = native_mod.crop_flip_normalize
    native_mod.crop_flip_normalize = lambda *a, **k: None
    try:
        out_np = augment.cifar_augment(dict(batch), np.random.default_rng(9))
    finally:
        native_mod.crop_flip_normalize = orig
    assert out_native["image"].dtype == np.float32
    np.testing.assert_allclose(
        out_native["image"], out_np["image"], atol=1e-5
    )
