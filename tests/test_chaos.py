"""Serving chaos tier (ISSUE 10): replica death is a normal input.

The load-bearing test is
:class:`TestChaosGolden::test_kill_one_of_three_zero_failed_requests`
— the acceptance contract: 3 REAL in-proc paged replicas (warmed AOT
ladders) under concurrent load, one killed mid-decode by a
deterministic ``crash@R:N`` fault. Every request completes 200 (the
router's in-flight failover replays the victims from the prompt on a
survivor), every stream — failed-over ones included — is
token-identical to the engine's unbatched reference, the supervisor
restores the fleet to 3 green replicas without operator action, and
the survivors take ZERO post-warmup recompiles.

Everything else here is deterministic harness coverage that doesn't
need a device: fault-spec parsing, forced BlockExhausted / transport /
poisoned-health faults against device-free fake engines, supervisor
transitions over a real child process (:class:`ProcessReplica`).
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from tensorflow_examples_tpu.serving import kv_cache
from tensorflow_examples_tpu.serving.chaos import ChaosFleet, RouterPair
from tensorflow_examples_tpu.serving.engine import ServeConfig
from tensorflow_examples_tpu.serving.router import (
    Router,
    RouterConfig,
    RouterFrontend,
)
from tensorflow_examples_tpu.serving.supervisor import (
    ProcessReplica,
    Supervisor,
)
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry
from tensorflow_examples_tpu.utils import faults as faults_mod

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


# ------------------------------------------------------------ fault specs


class TestServeFaultSpec:
    def test_parse_all_kinds(self):
        plan = faults_mod.parse_serve_spec(
            "crash@1:4,slowrep@0:0.25,transport@2:3,kvexhaust@0:7,"
            "badhealth@1:2"
        )
        assert plan.crash_at == {1: 4}
        assert plan.slow_replica == {0: 0.25}
        assert plan.transport_drop == {2: 3}
        assert plan.kvexhaust_at == {0: 7}
        assert plan.bad_health == {1: 2}

    def test_unknown_kind_and_malformed_args_raise(self):
        with pytest.raises(ValueError, match="unknown serve fault"):
            faults_mod.parse_serve_spec("explode@0:1")
        with pytest.raises(ValueError, match="needs '@<replica>:<arg>'"):
            faults_mod.parse_serve_spec("crash@3")
        with pytest.raises(ValueError, match="malformed"):
            faults_mod.parse_serve_spec("crash@a:b")

    def test_faults_fire_once_and_are_recorded(self, serve_faults):
        eng = serve_faults("transport@0:2,badhealth@1:1")
        assert eng.transport_fault(0) and eng.transport_fault(0)
        assert not eng.transport_fault(0)  # budget spent
        assert not eng.transport_fault(1)  # other replica untouched
        assert eng.health_fault(1) and not eng.health_fault(1)
        kinds = [k for k, _, _ in eng.fired]
        assert kinds.count("transport") == 2
        assert kinds.count("badhealth") == 1


# --------------------------------------------------- device-free harness


class _FakeEngine:
    """Deterministic device-free engine (test_router's, plus the ISSUE
    10 serve-fault hook and the warmup the chaos replica expects):
    token stream is prompt[-1]+1, +2, ... so every replica serves
    identical output and failover cannot change results."""

    def __init__(self, *, max_slots=4, max_queue=32, max_len=64,
                 step_delay=0.0, replica_id=0):
        self.cfg = ServeConfig(
            max_slots=max_slots, max_queue=max_queue, max_delay_s=0.0,
            request_timeout_s=30.0,
        )
        import serve_bench

        from tensorflow_examples_tpu.models import transformer

        base = dict(serve_bench.SMOKE_MODEL)
        base["max_len"] = max_len
        self.model_cfg = transformer.TransformerConfig(**base)
        self.registry = MetricsRegistry()
        self.pool = kv_cache.KVCachePool(
            num_layers=1, num_slots=max_slots, num_heads=1,
            max_len=max_len, head_dim=2, registry=self.registry,
        )
        self.step_delay = step_delay
        self.replica_id = replica_id
        self.warmed = False

    def warmup(self):
        self.warmed = True
        return {}

    def post_warmup_recompiles(self):
        return 0

    def prefill(self, slot, prompt, *, seed=0, temperature=0.0, top_k=0):
        self.pool.lengths[slot] = len(prompt)
        last = np.zeros((self.model_cfg.vocab_size,), np.float32)
        return (prompt[-1] + 1) % self.model_cfg.vocab_size, last

    def decode(self, entries):
        feng = faults_mod.serve_active()
        if feng is not None:
            # Mirror InferenceEngine.decode's hook site so the harness
            # tests exercise the same fault semantics device-free.
            feng.decode_step(self.replica_id, [e[0] for e in entries])
        if self.step_delay:
            time.sleep(self.step_delay)
        out = {}
        for slot, token, _seed, _temp, _tk in entries:
            self.pool.lengths[slot] += 1
            out[slot] = (token + 1) % self.model_cfg.vocab_size
        return out


def _fake_fleet(n=2, *, step_delay=0.0, router_cfg=None,
                supervisor_kw=None):
    def make_factory(k):
        return lambda: _FakeEngine(step_delay=step_delay, replica_id=k)

    fleet = ChaosFleet(
        [make_factory(k) for k in range(n)],
        router_cfg=router_cfg or RouterConfig(
            probe_interval_s=0.05, retry_budget_s=20.0, max_retries=4,
            eject_after=1, eject_cooldown_s=0.5,
        ),
        supervisor_kw=dict(
            poll_s=0.05, health_stall_s=2.0, warm_timeout_s=30.0,
        ) | (supervisor_kw or {}),
    )
    fleet.start()
    return fleet


def _post(url, body, timeout=30):
    import serve_bench

    return serve_bench._post_json(url, body, timeout)


class TestChaosHarnessFake:
    """Fault kinds + breaker/supervisor transitions, device-free."""

    @pytest.mark.timeout(120)
    def test_kill_eject_restart_readmit_transitions(self, serve_faults):
        """The chaos state machine end-to-end on fake engines: crash
        mid-decode -> transport failure -> breaker EJECTS (eject_after
        =1) -> supervisor detects, restarts, READMITS -> the restarted
        replica serves again."""
        serve_faults("crash@0:2")
        fleet = _fake_fleet(2, step_delay=0.005)
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            url = rfront.url("/generate")
            statuses = [
                _post(url, {"prompt": [i + 1], "max_new_tokens": 4})[0]
                for i in range(10)
            ]
            assert statuses.count(200) == 10, statuses
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/failovers_total", 0) >= 1
            assert counters.get("router/ejections_total", 0) >= 1
            assert fleet.await_fleet_green(2, timeout_s=30)
            events = [
                e for u, e in fleet.supervisor.events
                if u == fleet.replicas[0].url
            ]
            assert events[:3] == ["detected", "restarted", "readmitted"]
            assert sum(fleet.supervisor.restarts.values()) == 1
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/restarts_total", 0) == 1
            assert counters.get("router/readmits_total", 0) >= 1
            # The restarted replica takes traffic again.
            fleet.router.probe_once()
            status, reply = _post(
                url, {"prompt": [42], "max_new_tokens": 2}
            )
            assert status == 200 and reply["tokens"] == [43, 44]
        finally:
            rfront.close()
            fleet.close()

    @pytest.mark.timeout(120)
    def test_forced_block_exhaustion_fails_over(self, serve_faults):
        """kvexhaust@R:N: the paged pool's loud capacity path — the
        victim requests get 503 retry:true from the replica and the
        router re-runs them elsewhere; nothing fails."""
        serve_faults("kvexhaust@0:1")
        fleet = _fake_fleet(2, step_delay=0.005)
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            url = rfront.url("/generate")
            statuses = [
                _post(url, {"prompt": [i + 1], "max_new_tokens": 4})[0]
                for i in range(8)
            ]
            assert statuses.count(200) == 8, statuses
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/retries_total", 0) >= 1
            # Forced exhaustion is NOT a crash: the replica stays up.
            assert all(r.alive() for r in fleet.replicas)
        finally:
            rfront.close()
            fleet.close()

    @pytest.mark.timeout(120)
    def test_transport_fault_fails_over(self, serve_faults):
        serve_faults("transport@0:1")
        fleet = _fake_fleet(2)
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            url = rfront.url("/generate")
            statuses = [
                _post(url, {"prompt": [i + 1], "max_new_tokens": 2})[0]
                for i in range(6)
            ]
            assert statuses.count(200) == 6, statuses
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/failovers_total", 0) >= 1
        finally:
            rfront.close()
            fleet.close()

    @pytest.mark.timeout(120)
    def test_poisoned_health_marks_unhealthy_not_crash(
        self, serve_faults
    ):
        """badhealth@R:K: garbage /health bodies mark the replica
        unhealthy; the probe sweep survives and keeps probing the
        OTHER replicas (ISSUE 10 satellite regression)."""
        serve_faults(f"badhealth@0:{10}")
        fleet = _fake_fleet(
            2,
            router_cfg=RouterConfig(
                probe_interval_s=60.0, eject_after=1,
            ),
            supervisor_kw=dict(health_stall_s=3600.0),
        )
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            router = fleet.router
            for _ in range(router.cfg.unhealthy_after):
                router.probe_once()
            a, b = router.replicas
            assert a.failures >= router.cfg.unhealthy_after
            assert not a.eligible(router.cfg.unhealthy_after)
            # The sweep did NOT stop at the garbage replica.
            assert b.probed and b.failures == 0
            status, _ = _post(
                rfront.url("/generate"),
                {"prompt": [5], "max_new_tokens": 2},
            )
            assert status == 200
        finally:
            rfront.close()
            fleet.close()


# ------------------------------------------------- process supervision


CHILD_SERVER = """\
import http.server, json, sys

class H(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        body = json.dumps(
            {"ok": True, "queue_depth": 0, "kv_occupancy": 0.0}
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass

http.server.ThreadingHTTPServer(
    ("127.0.0.1", int(sys.argv[1])), H
).serve_forever()
"""


class TestProcessSupervision:
    @pytest.mark.timeout(120)
    def test_dead_process_restarted_and_readmitted(self, tmp_path):
        """ProcessReplica + Supervisor over a real child process: kill
        -9 the replica, one supervisor sweep respawns it and re-admits
        it only after /health is green again."""
        import socket

        script = tmp_path / "stub_replica.py"
        script.write_text(CHILD_SERVER)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        rep = ProcessReplica(
            f"{sys.executable} {script} {{port}}", port=port
        ).start()
        router = None
        sup = None
        try:
            deadline = time.monotonic() + 30
            from tensorflow_examples_tpu.serving.router import _get_json

            while time.monotonic() < deadline:
                if _get_json(rep.url + "/health", 1.0)[0] == 200:
                    break
                time.sleep(0.05)
            else:
                pytest.fail("stub replica never came up")
            router = Router(
                [rep.url], cfg=RouterConfig(probe_interval_s=60.0)
            )
            router.probe_once()
            sup = Supervisor(
                router, [rep], poll_s=0.05, health_stall_s=2.0,
                warm_timeout_s=30.0,
            )
            rep._proc.kill()  # SIGKILL: no drain, no goodbye
            rep._proc.wait(timeout=10)
            assert not rep.alive()
            sup.check_once()  # detect -> quarantine -> respawn -> green
            assert rep.alive()
            assert [e for _, e in sup.events] == [
                "detected", "restarted", "readmitted"
            ]
            assert not router.replicas[0].quarantined
            assert (
                router.registry.counter_values()[
                    "router/restarts_total"
                ] == 1
            )
            assert _get_json(rep.url + "/health", 2.0)[0] == 200
        finally:
            if sup is not None:
                sup.close()
            if router is not None:
                router.close()
            rep.close()


# ------------------------------------------- crash-loop abandonment


class TestCrashLoopAbandonment:
    """ISSUE 13 satellite: a replica that exhausts ``max_restarts``
    while traffic is in flight stays quarantined — the router never
    re-dispatches to it, and its in-flight requests fail over
    token-identically."""

    @pytest.mark.timeout(120)
    def test_crash_looping_replica_abandoned_under_load(
        self, serve_faults
    ):
        serve_faults("crash@0:2")
        builds = [0]

        def flaky_factory():
            # First build (fleet start) succeeds; every supervisor
            # restart of this replica fails — a crash-looping build.
            builds[0] += 1
            if builds[0] > 1:
                raise RuntimeError("crash-looping build")
            return _FakeEngine(step_delay=0.005, replica_id=0)

        fleet = ChaosFleet(
            [flaky_factory,
             lambda: _FakeEngine(step_delay=0.005, replica_id=1)],
            router_cfg=RouterConfig(
                probe_interval_s=0.05, retry_budget_s=20.0,
                max_retries=4, eject_after=1, eject_cooldown_s=0.5,
            ),
            supervisor_kw=dict(
                poll_s=0.05, health_stall_s=2.0, warm_timeout_s=30.0,
                max_restarts=2, restart_backoff_s=0.01,
            ),
        )
        fleet.start()
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            import serve_bench

            url = rfront.url("/generate")
            n, max_new = 10, 4
            prompts = [[3 * i + 1] for i in range(n)]
            # Concurrent load across the kill: replica 0 dies
            # mid-decode (crash@0:2) and every restart attempt fails.
            out = serve_bench.drive(
                None, prompts, concurrency=4, max_new=max_new,
                temperature=0.0, top_k=0, http_url=url, timeout=30.0,
            )
            vocab = fleet.replicas[1].engine.model_cfg.vocab_size
            for prompt, reply in zip(prompts, out["replies"]):
                assert reply is not None and reply[0] == 200, reply
                # Token-identical failover: the fake stream is a pure
                # function of the prompt, so a replayed victim matches.
                assert reply[1]["tokens"] == [
                    (prompt[-1] + 1 + j) % vocab for j in range(max_new)
                ]
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/failovers_total", 0) >= 1
            # The supervisor exhausts max_restarts and gives up.
            url0 = fleet.replicas[0].url
            deadline = time.monotonic() + 30
            while (
                url0 not in fleet.supervisor.given_up
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert url0 in fleet.supervisor.given_up
            events = [
                e for u, e in fleet.supervisor.events if u == url0
            ]
            assert events[0] == "detected"
            assert events[-1] == "gave_up"
            assert "readmitted" not in events
            # Abandoned = quarantined, ineligible, never restarted.
            state0 = fleet.router._find(url0)
            assert state0.quarantined
            assert not state0.eligible(fleet.router.cfg.unhealthy_after)
            assert fleet.supervisor.restarts[url0] == 0
            assert counters.get("router/restarts_total", 0) == 0
            # The router never re-dispatches to the abandoned replica:
            # follow-up traffic serves 200 off the survivor alone.
            dispatched_before = state0.dispatched
            for i in range(4):
                status, reply = _post(
                    url, {"prompt": [50 + i], "max_new_tokens": 2}
                )
                assert status == 200
                assert reply["tokens"] == [
                    (50 + i + 1 + j) % vocab for j in range(2)
                ]
            assert state0.dispatched == dispatched_before
        finally:
            rfront.close()
            fleet.close()


# --------------------------------------------------- THE chaos golden


CHAOS_MODEL = dict(
    vocab_size=211,
    max_len=32,
    num_layers=1,
    num_heads=2,
    d_model=16,
    dropout=0.0,
    attention="xla",
)


def _real_engine_factory(spec_decode_k: int = 0, role: str = "mixed"):
    """Tiny REAL paged engine for the golden: small enough that three
    warmups + one supervisor re-warm stay tier-1 friendly, real enough
    that the token-identity and zero-recompile claims mean something.
    ``spec_decode_k`` arms speculative decoding (ISSUE 11) — the chaos
    contract must hold with the verify path on the hot loop too.
    ``role`` builds the heterogeneous prefill/decode fleets of the
    ISSUE 12 golden."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.models import transformer
    from tensorflow_examples_tpu.serving.engine import InferenceEngine

    cfg = transformer.TransformerConfig(**CHAOS_MODEL)
    model = transformer.Transformer(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, tokens
    )["params"]
    return InferenceEngine(
        cfg,
        params,
        cfg=ServeConfig(
            max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=16,
            kv_block_size=8, max_delay_s=0.0, request_timeout_s=60.0,
            spec_decode_k=spec_decode_k, role=role,
        ),
        registry=MetricsRegistry(),
    )


def _spec_engine_factory():
    return _real_engine_factory(spec_decode_k=2)


def _prefill_engine_factory():
    return _real_engine_factory(role="prefill")


def _decode_engine_factory():
    return _real_engine_factory(role="decode")


class TestChaosGolden:
    @pytest.mark.timeout(480)
    def test_kill_one_of_three_zero_failed_requests(self, serve_faults):
        """ISSUE 10 acceptance: 3 in-proc paged replicas under
        concurrent load; killing one mid-decode yields ZERO failed
        requests, every replayed stream token-identical to the
        unbatched reference, the supervisor restores the fleet to 3
        healthy replicas, and the survivors take zero post-warmup
        recompiles."""
        import serve_bench

        fault_engine = serve_faults("crash@1:3")
        fleet = ChaosFleet(
            [_real_engine_factory] * 3,
            router_cfg=RouterConfig(
                probe_interval_s=0.1, retry_budget_s=30.0,
                max_retries=4, eject_after=1, eject_cooldown_s=1.0,
            ),
            supervisor_kw=dict(
                poll_s=0.05, health_stall_s=3.0, warm_timeout_s=240.0,
            ),
        )
        fleet.start()
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            n, max_new = 12, 6
            prompts = serve_bench.make_prompts(
                n, vocab=CHAOS_MODEL["vocab_size"],
                max_len=CHAOS_MODEL["max_len"], max_new=max_new,
                seed=7, shared_prefix_every=4,
            )
            out = serve_bench.drive(
                None, prompts, concurrency=4, max_new=max_new,
                temperature=0.7, top_k=0,
                http_url=rfront.url("/generate"), timeout=60.0,
            )
            statuses = [
                r[0] if r is not None else None for r in out["replies"]
            ]
            # ZERO failed requests across the replica kill.
            assert statuses.count(200) == n, statuses
            # The kill actually happened, mid-decode, and victims were
            # failed over (replayed from the prompt elsewhere).
            assert ("crash", 1, 3) in fault_engine.fired
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/failovers_total", 0) >= 1
            assert counters.get("router/ejections_total", 0) >= 1
            # Every stream — failed-over ones included — is
            # token-identical to the unbatched reference (the
            # per-request fold_in seeding makes replay invisible).
            ref_engine = fleet.replicas[0].engine
            for i, prompt in enumerate(prompts):
                expect = ref_engine.reference_generate(
                    prompt, max_new=max_new, seed=i,
                    temperature=0.7, top_k=0,
                )
                got = out["replies"][i][1]["tokens"]
                assert got == expect, (
                    f"request {i} diverged after failover: "
                    f"{got} != {expect}"
                )
            # The supervisor restores the fleet: restart -> re-warm ->
            # /health green -> readmit, no operator action.
            assert fleet.await_fleet_green(3, timeout_s=240)
            events = [
                e for u, e in fleet.supervisor.events
                if u == fleet.replicas[1].url
            ]
            assert events[:3] == ["detected", "restarted", "readmitted"]
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/restarts_total", 0) == 1
            # Zero post-warmup recompiles on the survivors (and on the
            # freshly re-warmed replica).
            for rep in fleet.replicas:
                assert rep.engine.post_warmup_recompiles() == 0
            # The fleet serves after restoration — including the
            # restarted replica's slot in the rotation.
            for i in range(4):
                status, reply = _post(
                    rfront.url("/generate"),
                    {"prompt": [3 + i], "max_new_tokens": 2,
                     "seed": 99 + i},
                )
                assert status == 200
            # Schema v7: the router's stats line carries the
            # fault-tolerance counters and validates.
            line = json.loads(json.dumps(fleet.router.stats_line()))
            assert schema.validate_line(line) == []
            assert line["schema_version"] == schema.SERVING_SCHEMA_VERSION
            assert line["serving"]["router_failovers"] >= 1
            assert line["serving"]["router_ejections"] >= 1
            assert line["serving"]["router_restarts"] == 1
        finally:
            rfront.close()
            fleet.close()

    @pytest.mark.timeout(480)
    def test_kill_one_of_three_with_speculation_on(self, serve_faults):
        """ISSUE 11 acceptance: the kill-one-of-three chaos contract
        holds with SPECULATIVE decoding enabled (spec_decode_k=2) —
        zero failed requests, and every failover replay token-identical
        to the unbatched reference. Speculation is seed-deterministic
        per position, so a victim replayed from the prompt on a
        survivor commits exactly the same stream no matter how its
        draft windows land."""
        import serve_bench

        fault_engine = serve_faults("crash@1:3")
        fleet = ChaosFleet(
            [_spec_engine_factory] * 3,
            router_cfg=RouterConfig(
                probe_interval_s=0.1, retry_budget_s=30.0,
                max_retries=4, eject_after=1, eject_cooldown_s=1.0,
            ),
            supervisor_kw=dict(
                poll_s=0.05, health_stall_s=3.0, warm_timeout_s=240.0,
            ),
        )
        fleet.start()
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            n, max_new = 10, 5
            prompts = serve_bench.make_prompts(
                n, vocab=CHAOS_MODEL["vocab_size"],
                max_len=CHAOS_MODEL["max_len"], max_new=max_new,
                seed=17, shared_prefix_every=4,
            )
            out = serve_bench.drive(
                None, prompts, concurrency=3, max_new=max_new,
                temperature=0.7, top_k=0,
                http_url=rfront.url("/generate"), timeout=60.0,
            )
            statuses = [
                r[0] if r is not None else None for r in out["replies"]
            ]
            assert statuses.count(200) == n, statuses
            assert ("crash", 1, 3) in fault_engine.fired
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/failovers_total", 0) >= 1
            ref_engine = fleet.replicas[0].engine
            for i, prompt in enumerate(prompts):
                expect = ref_engine.reference_generate(
                    prompt, max_new=max_new, seed=i,
                    temperature=0.7, top_k=0,
                )
                got = out["replies"][i][1]["tokens"]
                assert got == expect, (
                    f"speculative request {i} diverged after failover: "
                    f"{got} != {expect}"
                )
            assert fleet.await_fleet_green(3, timeout_s=240)
            for rep in fleet.replicas:
                assert rep.engine.post_warmup_recompiles() == 0
        finally:
            rfront.close()
            fleet.close()

    @pytest.mark.timeout(480)
    def test_kill_prefill_replica_mid_handoff(self, serve_faults):
        """ISSUE 12 acceptance: a HETEROGENEOUS fleet (1 prefill + 2
        decode replicas) serves through the prefill->decode KV-page
        handoff; killing the prefill replica mid-handoff (its fault
        schedule counts prefills — the prefill-role unit of work)
        yields ZERO failed requests: the router falls back to full
        /generate on the decode replicas (roles are advisory, so the
        failover is ordinary), every stream stays token-identical to
        the unbatched reference, and the supervisor restores the
        prefill replica — role preserved — without operator action."""
        import serve_bench

        fault_engine = serve_faults("crash@0:2")
        fleet = ChaosFleet(
            [_prefill_engine_factory, _decode_engine_factory,
             _decode_engine_factory],
            router_cfg=RouterConfig(
                probe_interval_s=0.1, retry_budget_s=30.0,
                max_retries=4, eject_after=1, eject_cooldown_s=1.0,
            ),
            supervisor_kw=dict(
                poll_s=0.05, health_stall_s=3.0, warm_timeout_s=240.0,
            ),
        )
        fleet.start()
        assert fleet.role_census() == {"prefill": 1, "decode": 2}
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            # The probe sweep must learn the role topology before the
            # first dispatch exercises the handoff path.
            deadline = time.monotonic() + 30
            while (
                not fleet.router._disagg_ready()
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert fleet.router._disagg_ready()
            n, max_new = 10, 5
            prompts = serve_bench.make_prompts(
                n, vocab=CHAOS_MODEL["vocab_size"],
                max_len=CHAOS_MODEL["max_len"], max_new=max_new,
                seed=29, shared_prefix_every=4,
            )
            out = serve_bench.drive(
                None, prompts, concurrency=3, max_new=max_new,
                temperature=0.7, top_k=0,
                http_url=rfront.url("/generate"), timeout=60.0,
            )
            statuses = [
                r[0] if r is not None else None for r in out["replies"]
            ]
            # ZERO failed requests across the prefill-replica kill.
            assert statuses.count(200) == n, statuses
            # The kill actually happened, mid-prefill on the prefill
            # replica, and the router failed over.
            assert ("crash", 0, 2) in fault_engine.fired
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/failovers_total", 0) >= 1
            # Handoffs completed before the kill (the topology was
            # exercised, not just built).
            assert counters.get("router/handoffs_total", 0) >= 1
            # Token-identical — handed-off, failed-over, and fallback
            # full-path streams alike (pure function of params/prompt/
            # seed).
            ref_engine = fleet.replicas[1].engine
            for i, prompt in enumerate(prompts):
                expect = ref_engine.reference_generate(
                    prompt, max_new=max_new, seed=i,
                    temperature=0.7, top_k=0,
                )
                got = out["replies"][i][1]["tokens"]
                assert got == expect, (
                    f"request {i} diverged across the handoff kill: "
                    f"{got} != {expect}"
                )
            # The supervisor restores the fleet — the restarted
            # replica comes back with its PREFILL role.
            assert fleet.await_fleet_green(3, timeout_s=240)
            events = [
                e for u, e in fleet.supervisor.events
                if u == fleet.replicas[0].url
            ]
            assert events[:3] == ["detected", "restarted", "readmitted"]
            assert fleet.role_census() == {"prefill": 1, "decode": 2}
            for rep in fleet.replicas:
                assert rep.engine.post_warmup_recompiles() == 0
            # Post-restore, the handoff path serves again.
            fleet.router.probe_once()
            handoffs_before = counters.get("router/handoffs_total", 0)
            status, reply = _post(
                rfront.url("/generate"),
                {"prompt": [11, 12, 13], "max_new_tokens": 3,
                 "seed": 77},
            )
            assert status == 200
            assert reply["tokens"] == ref_engine.reference_generate(
                [11, 12, 13], max_new=3, seed=77
            )
            counters = fleet.router.registry.counter_values()
            assert counters.get(
                "router/handoffs_total", 0
            ) > handoffs_before
        finally:
            rfront.close()
            fleet.close()

    @pytest.mark.timeout(480)
    def test_decode_crash_yields_one_stitched_trace(self, serve_faults):
        """ISSUE 18 acceptance: a disaggregated fleet (1 prefill + 2
        decode) under chaos — a decode replica crashes mid-decode —
        leaves ONE stitched trace for the failed-over request: the
        dead attempt's leg span (transport status 0) and the answering
        one side by side under the same root, the replica-side
        queue/prefill/decode segments nested under the attempt that
        carried them, root wall ≈ the client-measured e2e, zero
        post-warmup recompiles, and tools/trace_report.py's critical
        path walking into the leg that ANSWERED, not the dead one."""
        import serve_bench
        import trace_report

        fault_engine = serve_faults("crash@1:3")
        fleet = ChaosFleet(
            [_prefill_engine_factory, _decode_engine_factory,
             _decode_engine_factory],
            router_cfg=RouterConfig(
                probe_interval_s=0.1, retry_budget_s=30.0,
                max_retries=4, eject_after=1, eject_cooldown_s=1.0,
                # A chaos golden inspects EVERY trace — no sampler coin.
                trace_sample_fraction=1.0,
            ),
            supervisor_kw=dict(
                poll_s=0.05, health_stall_s=3.0, warm_timeout_s=240.0,
            ),
        )
        fleet.start()
        assert fleet.role_census() == {"prefill": 1, "decode": 2}
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            deadline = time.monotonic() + 30
            while (
                not fleet.router._disagg_ready()
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert fleet.router._disagg_ready()
            n, max_new = 10, 5
            prompts = serve_bench.make_prompts(
                n, vocab=CHAOS_MODEL["vocab_size"],
                max_len=CHAOS_MODEL["max_len"], max_new=max_new,
                seed=37, shared_prefix_every=4,
            )
            out = serve_bench.drive(
                None, prompts, concurrency=3, max_new=max_new,
                temperature=0.7, top_k=0,
                http_url=rfront.url("/generate"), timeout=60.0,
            )
            statuses = [
                r[0] if r is not None else None for r in out["replies"]
            ]
            assert statuses.count(200) == n, statuses
            # The decode replica died mid-decode and the router failed
            # the victims over.
            assert ("crash", 1, 3) in fault_engine.fired
            counters = fleet.router.registry.counter_values()
            assert counters.get("router/failovers_total", 0) >= 1
            assert counters.get("router/handoffs_total", 0) >= 1
            # Every reply names its trace, and every trace finished.
            docs = []
            for i, (status, reply) in enumerate(out["replies"]):
                doc = fleet.router.recorder.get(reply["trace_id"])
                assert doc is not None and not doc.get("open"), i
                docs.append(doc)
            failed_over = [
                d for d in docs if "failover" in d["flags"]
            ]
            assert failed_over, [d["flags"] for d in docs]
            doc = failed_over[0]
            idx = docs.index(doc)
            names = [s["name"] for s in doc["spans"]]
            # ONE tree: a single root covering the whole request.
            assert names.count("request") == 1
            root = next(
                s for s in doc["spans"] if s["name"] == "request"
            )
            # Both attempts of the interrupted hop are in the tree —
            # the dead one (transport, status 0) AND the one that
            # answered — whether the router retried the leg or fell
            # back to the full path.
            attempts = [
                s for s in doc["spans"]
                if s["name"] in ("prefill_leg", "resume_leg", "dispatch")
            ]
            assert len(attempts) >= 2, names
            att_statuses = [s["tags"]["status"] for s in attempts]
            assert 0 in att_statuses, att_statuses
            assert 200 in att_statuses, att_statuses
            # Replica-side segments crossed the wire and nest under an
            # attempt span (never float at the root).
            attempt_ids = {s["span_id"] for s in attempts}
            segs = [
                s for s in doc["spans"]
                if s["name"] in ("queue_wait", "prefill",
                                 "prefill_chunk", "decode_segment",
                                 "resume_import")
            ]
            assert any(s["name"] == "queue_wait" for s in segs), names
            assert any(
                s["name"] == "decode_segment" for s in segs
            ), names
            assert all(
                s["parent_id"] in attempt_ids for s in segs
            ), names
            # The span tree accounts for the client's wall: the root
            # covers (almost all of) the measured e2e — transport
            # overhead is the only slack.
            client = out["client_s"][idx]
            assert root["dur_s"] <= client + 0.05
            assert root["dur_s"] >= 0.5 * client, (
                root["dur_s"], client
            )
            # The attribution tool walks the path that ANSWERED: the
            # dead attempt ended early, so the critical path (latest
            # finisher chain) goes through the 200 leg.
            path = trace_report.critical_path(doc)
            assert path and path[0]["name"] == "request"
            leg_row = next(
                r for r in path
                if r["name"] in ("prefill_leg", "resume_leg", "dispatch")
            )
            assert leg_row["tags"]["status"] == 200, path
            # Forced keep: a failed-over trace is never sampled away.
            assert doc["kept"] is True
            assert doc["keep_reason"] in ("failover", "retried", "slow")
            # Fleet restored; zero post-warmup recompiles everywhere.
            assert fleet.await_fleet_green(3, timeout_s=240)
            for rep in fleet.replicas:
                assert rep.engine.post_warmup_recompiles() == 0
            # The v13 stats line tells the same story and validates.
            line = json.loads(json.dumps(fleet.router.stats_line()))
            assert schema.validate_line(line) == []
            serving = line["serving"]
            assert serving["traces_kept"] >= n
            assert serving["trace_coverage"] == 1.0
        finally:
            rfront.close()
            fleet.close()


# ------------------------------------- ISSUE 16: the control plane dies


class TestRouterPairFake:
    """Takeover mechanics over device-free fake replicas: the full
    RouterPair choreography (journal, lease, killrouter, promotion,
    client failover, dedupe, split-brain fence) at O(ms) per request.
    The real-engine version with token-identity is TestTakeoverGolden."""

    @pytest.mark.timeout(120)
    def test_killrouter_takeover_zero_lost_requests(
        self, serve_faults, tmp_path
    ):
        import serve_bench

        fault_engine = serve_faults("killrouter@3")
        fleet = _fake_fleet(2)
        pair = RouterPair(
            fleet.urls,
            journal_path=str(tmp_path / "journal.jsonl"),
            lease_path=str(tmp_path / "lease.json"),
            router_cfg=fleet.router_cfg,
            standby_interval_s=0.05,
            miss_budget_s=0.3,
        )
        pair.supervisor = fleet.supervisor
        pair.start()
        try:
            n, max_new = 8, 4
            prompts = serve_bench.make_prompts(
                n, vocab=211, max_len=64, max_new=max_new, seed=11,
            )
            out = serve_bench._drive_takeover(
                pair.endpoints(), prompts, concurrency=3,
                max_new=max_new, temperature=0.0, top_k=0,
                timeout=30.0,
            )
            statuses = [
                r[0] if r is not None else None for r in out["replies"]
            ]
            # ZERO lost accepted requests across the router kill: the
            # client's two-endpoint retry loop plus the journal absorb
            # it.
            assert statuses.count(200) == n, statuses
            assert any(k == "killrouter" for k, _, _ in fault_engine.fired)
            # The standby serves as soon as it holds the lease — replay
            # may still be in flight when the drive returns, so wait
            # for promote() to finish rather than sampling the event.
            assert pair.monitor.promoted.wait(10.0)
            assert pair.monitor.takeover_latency_s is not None
            # The dispatch the kill interrupted was left incomplete in
            # the journal and replayed by the promoted standby.
            assert pair.monitor.replayed >= 1
            # The supervisor now reports restarts to the NEW active
            # router (adopt_router on promotion).
            assert fleet.supervisor.router is pair.standby
            # Nothing is left on the replay worklist.
            assert pair.journal.incomplete() == []
            # Explicit idempotent retry against the active endpoint:
            # original tokens, dedup-flagged, no second generation.
            orig = out["replies"][0][1]["tokens"]
            status, dup = _post(pair.endpoints()[1], {
                "prompt": prompts[0], "max_new_tokens": max_new,
                "seed": 0, "request_id": "tko-0",
            })
            assert status == 200 and dup.get("dedup") is True
            assert dup["tokens"] == orig
            counters = pair.registry.counter_values()
            assert counters.get("router/dedup_hits_total", 0) >= 1
            assert counters.get("router/takeover_total", 0) == 1
            # Resume: the remainder of the SAME stream from an offset.
            status, res = _post(pair.endpoints()[1], {
                "prompt": prompts[0], "max_new_tokens": max_new,
                "seed": 0, "request_id": "tko-0", "resume_from": 2,
            })
            assert status == 200 and res["tokens"] == orig[2:]
            assert res.get("resumed") is True
        finally:
            pair.close()
            fleet.close()

    @pytest.mark.timeout(120)
    def test_split_brain_fenced_dispatch_refused(self, tmp_path):
        """The split-brain pin: a primary that STALLS (misses its
        heartbeats without dying) is fenced by the promoted standby's
        newer token — its own dispatch path refuses to serve, so no
        request is ever handled by two routers."""
        fleet = _fake_fleet(2)
        pair = RouterPair(
            fleet.urls,
            journal_path=str(tmp_path / "journal.jsonl"),
            lease_path=str(tmp_path / "lease.json"),
            router_cfg=fleet.router_cfg,
            standby_interval_s=0.05,
            miss_budget_s=0.2,
        )
        pair.start()
        try:
            # The live primary serves.
            status, reply = _post(pair.endpoints()[0], {
                "prompt": [7], "max_new_tokens": 2,
            })
            assert status == 200 and reply["tokens"] == [8, 9]
            # Simulate the stall: stop the primary's loops (heartbeats
            # cease) WITHOUT closing its HTTP frontend — the process is
            # alive, just not heartbeating (GC pause, CPU starvation).
            pair.primary.close()
            deadline = time.monotonic() + 30
            while (
                not pair.monitor.promoted.is_set()
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert pair.monitor.promoted.is_set()
            # The revived primary's dispatch is REFUSED: retryable
            # fenced 503, counter stamped — the same check that kept
            # the standby passive before promotion.
            assert pair.primary.fenced()
            status, body = _post(pair.endpoints()[0], {
                "prompt": [7], "max_new_tokens": 2,
            })
            assert status == 503 and body.get("fenced") is True
            assert body.get("retry") is True
            counters = pair.registry.counter_values()
            assert counters.get("router/fenced_dispatch_total", 0) >= 1
            # Its stale heartbeat can never clobber the new lease.
            assert pair.lease.heartbeat(1) is False
            assert pair.lease.read()["token"] == 2
            # The promoted standby serves the same request correctly.
            status, reply = _post(pair.endpoints()[1], {
                "prompt": [7], "max_new_tokens": 2,
            })
            assert status == 200 and reply["tokens"] == [8, 9]
        finally:
            pair.close()
            fleet.close()


class TestTakeoverGolden:
    @pytest.mark.timeout(480)
    def test_killrouter_mid_stream_zero_lost_token_identical(
        self, serve_faults, tmp_path
    ):
        """ISSUE 16 acceptance: a 2-replica REAL fleet with a
        primary/standby router pair under concurrent sampled load;
        ``killrouter`` fires mid-stream. The standby promotes within
        the heartbeat budget, ZERO accepted requests are lost, every
        stream — died-in-flight, journal-replayed, client-retried —
        is token-identical to the unbatched reference, a duplicated
        request_id retry returns the ORIGINAL tokens as a dedupe hit
        (no second generation), the fleet takes zero post-warmup
        recompiles, and the v12 stats line validates."""
        import serve_bench

        fault_engine = serve_faults("killrouter@3")
        fleet = ChaosFleet(
            [_real_engine_factory] * 2,
            router_cfg=RouterConfig(
                probe_interval_s=0.1, retry_budget_s=30.0,
                max_retries=4, eject_after=2, eject_cooldown_s=1.0,
            ),
            supervisor_kw=dict(
                poll_s=0.05, health_stall_s=3.0, warm_timeout_s=240.0,
            ),
        )
        fleet.start()
        miss_budget_s = 1.0
        pair = RouterPair(
            fleet.urls,
            journal_path=str(tmp_path / "journal.jsonl"),
            lease_path=str(tmp_path / "lease.json"),
            router_cfg=fleet.router_cfg,
            standby_interval_s=0.1,
            miss_budget_s=miss_budget_s,
        )
        pair.supervisor = fleet.supervisor
        pair.start()
        try:
            n, max_new = 10, 6
            prompts = serve_bench.make_prompts(
                n, vocab=CHAOS_MODEL["vocab_size"],
                max_len=CHAOS_MODEL["max_len"], max_new=max_new,
                seed=23, shared_prefix_every=4,
            )
            out = serve_bench._drive_takeover(
                pair.endpoints(), prompts, concurrency=4,
                max_new=max_new, temperature=0.7, top_k=0,
                timeout=60.0,
            )
            statuses = [
                r[0] if r is not None else None for r in out["replies"]
            ]
            # ZERO lost accepted requests across the router kill.
            assert statuses.count(200) == n, statuses
            assert any(
                k == "killrouter" for k, _, _ in fault_engine.fired
            )
            # The standby promoted, within the heartbeat budget (the
            # promotion verb itself: acquire + sweep + replay). Clients
            # can drain against the lease-holding standby before replay
            # completes, so wait for the event instead of sampling it.
            assert pair.monitor.promoted.wait(10.0)
            latency = pair.monitor.takeover_latency_s
            assert latency is not None and latency <= miss_budget_s * 10
            # The interrupted dispatch replayed from the journal.
            assert pair.monitor.replayed >= 1
            assert pair.journal.incomplete() == []
            # Every stream is token-identical to the unbatched
            # reference — takeover, replay, and client retries are
            # invisible in the tokens (pure function of params/prompt/
            # seed).
            ref_engine = fleet.replicas[0].engine
            for i, prompt in enumerate(prompts):
                expect = ref_engine.reference_generate(
                    prompt, max_new=max_new, seed=i,
                    temperature=0.7, top_k=0,
                )
                got = out["replies"][i][1]["tokens"]
                assert got == expect, (
                    f"request {i} diverged across takeover: "
                    f"{got} != {expect}"
                )
            # Idempotency: duplicate request_id returns the ORIGINAL
            # stream as a dedupe hit — no second generation burned.
            dispatched_before = pair.registry.counter_values().get(
                "router/dispatched_total", 0
            )
            orig = out["replies"][0][1]["tokens"]
            status, dup = _post(pair.endpoints()[1], {
                "prompt": prompts[0], "max_new_tokens": max_new,
                "temperature": 0.7, "top_k": 0, "seed": 0,
                "request_id": "tko-0",
            })
            assert status == 200 and dup.get("dedup") is True
            assert dup["tokens"] == orig
            counters = pair.registry.counter_values()
            assert counters.get("router/dedup_hits_total", 0) >= 1
            assert counters.get(
                "router/dispatched_total", 0
            ) == dispatched_before
            # Stitched ACROSS routers (ISSUE 18): the journal's done
            # record carries the original request's trace_id; the
            # promoted router's dedupe fast path adopts it, so the
            # duplicate's reply names the ORIGINAL trace and the
            # pair-shared recorder holds ONE merged tree — the
            # original pass's spans plus the dedupe hit.
            orig_tid = pair.journal.lookup("tko-0")["trace_id"]
            assert isinstance(orig_tid, str) and orig_tid
            assert dup["trace_id"] == orig_tid
            tdoc = pair.recorder.get(orig_tid)
            assert tdoc is not None and not tdoc.get("open")
            tnames = [s["name"] for s in tdoc["spans"]]
            assert "dedupe_hit" in tnames
            assert tnames.count("request") >= 2  # both passes' roots
            assert "deduped" in tdoc["flags"]
            assert tdoc["kept"] is True
            # Zero post-warmup recompiles fleet-wide.
            for rep in fleet.replicas:
                assert rep.engine.post_warmup_recompiles() == 0
            # The promoted router's stats line is schema-v12 and tells
            # the whole story (shared registry survives the switch).
            line = json.loads(json.dumps(pair.standby.stats_line()))
            assert schema.validate_line(line) == []
            assert line["schema_version"] == 14
            serving = line["serving"]
            assert serving["takeover_total"] == 1
            assert serving["journal_appends"] >= 2 * n
            assert serving["dedup_hits"] >= 1
            assert serving["takeover_latency_s"] == pytest.approx(
                latency
            )
            # Split-brain coda: the dead primary's fencing token is
            # stale — were it revived, its dispatch path refuses.
            assert pair.primary.fenced()
            status, body = pair.primary.handle(
                {"prompt": [5], "max_new_tokens": 2}, kind="generate"
            )
            assert status == 503 and body.get("fenced") is True
        finally:
            pair.close()
            fleet.close()


class TestAlertGolden:
    """ISSUE 19's chaos acceptance golden: inject a latency fault into
    one replica of a healthy fleet -> the SLO engine walks pending ->
    firing with an alert that names the SLO class and carries a
    resolvable worst-offender exemplar whose trace names the sick
    replica -> clear the fault -> the alert resolves after sustained
    health. The whole episode lands in the v14 alert sink."""

    @pytest.mark.timeout(300)
    def test_latency_fault_fires_then_resolves(
        self, serve_faults, tmp_path
    ):
        from tensorflow_examples_tpu.telemetry.slo import (
            AlertEngine,
            SLOConfig,
            SLOObjective,
        )

        # Replica 0 sleeps 0.25 s at EVERY decode step: ~0.75 s per
        # 3-token request against a 0.2 s e2e ceiling.
        serve_faults("slowrep@0:0.25")
        fleet = _fake_fleet(2, router_cfg=RouterConfig(
            probe_interval_s=0.05, retry_budget_s=20.0, max_retries=4,
            eject_after=4, eject_cooldown_s=0.5,
            trace_sample_fraction=1.0,
        ))
        path = str(tmp_path / "alerts.jsonl")
        # Chaos-tier windows: seconds, not minutes, and no dwell on the
        # firing edge (two evaluate ticks suffice).
        fleet.router.alerts = AlertEngine(
            SLOConfig(
                objectives=(SLOObjective(slo="interactive",
                                         e2e_p95_s=0.2,
                                         error_budget=0.1),),
                windows_s=(0.5, 2.0), burn_thresholds=(2.0, 1.0),
                pending_for_s=0.0, resolve_after_s=0.2,
            ),
            registry=fleet.router.registry, path=path,
        )
        rfront = RouterFrontend(fleet.router, port=0).start()
        try:
            url = rfront.url("/generate")
            deadline = time.time() + 90
            fired = None
            while fired is None and time.time() < deadline:
                for i in range(4):
                    status, _ = _post(
                        url, {"prompt": [i + 2], "max_new_tokens": 3}
                    )
                    assert status == 200
                for a in fleet.router.alerts.evaluate():
                    if (a["name"] == "e2e_interactive"
                            and a["state"] == "firing"):
                        fired = a
            assert fired is not None, "alert never fired under fault"
            # The alert names the SLO class and carries the exemplar.
            assert fired["slo"] == "interactive"
            assert fired["severity"] in ("page", "ticket")
            assert fired["burn_rate"] >= 2.0
            assert fired["value"] > 0.2  # the worst offender's e2e
            tid = fired.get("trace_id")
            assert isinstance(tid, str) and tid
            # The exemplar RESOLVES: the recorder holds the trace, and
            # its dispatch leg names the sick replica — alert ->
            # trace_report --trace-id is one copy-paste.
            tdoc = fleet.router.recorder.get(tid)
            assert tdoc is not None and not tdoc.get("open")
            legs = [
                s for s in tdoc["spans"]
                if (s.get("tags") or {}).get("replica")
            ]
            assert legs, tdoc["spans"]
            assert legs[-1]["tags"]["replica"] == fleet.replicas[0].url
            # Clear the fault: organic traffic goes healthy, the burn
            # drains out of the fast window, and the rule resolves.
            faults_mod.serve_clear()
            resolved = None
            deadline = time.time() + 90
            while resolved is None and time.time() < deadline:
                for i in range(4):
                    _post(url, {"prompt": [i + 2],
                                "max_new_tokens": 3})
                time.sleep(0.1)
                for a in fleet.router.alerts.evaluate():
                    if (a["name"] == "e2e_interactive"
                            and a["state"] == "resolved"):
                        resolved = a
            assert resolved is not None, "alert never resolved"
            stats = fleet.router.alerts.stats()
            assert stats["alerts_firing"] == 0
            assert stats["alert_count"] >= 1
            # The episode is durable: firing AND resolved transitions
            # in the sink, every line schema-v14 valid.
            with open(path) as f:
                lines = [json.loads(ln) for ln in f if ln.strip()]
            states = [ln["alert"]["state"] for ln in lines]
            assert "firing" in states and "resolved" in states
            for ln in lines:
                assert ln["schema_version"] == 14
                assert schema.validate_line(ln) == [], ln
            # Zero post-warmup recompiles fleet-wide (the standing
            # serving acceptance bar).
            for rep in fleet.replicas:
                assert rep.engine.post_warmup_recompiles() == 0
        finally:
            rfront.close()
            fleet.close()
