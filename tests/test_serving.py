"""Serving engine (ISSUE 5): KV pool, engine parity, continuous
batching golden, flow control, HTTP frontend, SIGTERM drain.

The load-bearing test is :class:`TestContinuousBatchingGolden`: ≥20
mixed-length generate requests — different prompt lengths, different
sampling settings — coalesced by the continuous batcher into shared
device batches must come out TOKEN-IDENTICAL to the engine's unbatched
single-request reference replay (which shares no batching, bucketing,
or KV-cache machinery with the serving path), with exactly the bucket-ladder
compiles and zero post-warmup recompiles. That is the whole serving
claim: batching is a throughput optimization, never a numerics change.
"""

import concurrent.futures
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tensorflow_examples_tpu.models import transformer
from tensorflow_examples_tpu.serving import kv_cache, paged_kv
from tensorflow_examples_tpu.serving.batcher import (
    ContinuousBatcher,
    DeadlineExceeded,
    Draining,
    QueueFull,
    Request,
)
from tensorflow_examples_tpu.serving.paged_kv import (
    BlockExhausted,
    PagedKVPool,
)
from tensorflow_examples_tpu.serving.engine import (
    EngineStepError,
    InferenceEngine,
    ServeConfig,
    top_logprobs,
)
from tensorflow_examples_tpu.serving.frontend import (
    ServingFrontend,
    run_until_preempted,
)
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import serve_bench  # noqa: E402 — needs the tools path above


def tiny_cfg(**kw):
    """The CI smoke model (tools/serve_bench.SMOKE_MODEL) as a
    TransformerConfig — one source of truth, so the unit suite and the
    serve_bench smoke can never de-sync."""
    base = dict(serve_bench.SMOKE_MODEL)
    base.update(kw)
    return transformer.TransformerConfig(**base)


@pytest.fixture(scope="module")
def warm_engine():
    """One warmed engine for the whole module (the AOT warmup is the
    expensive part; every test that borrows it must leave the pool
    empty — asserted at teardown)."""
    import jax
    import jax.numpy as jnp

    cfg = tiny_cfg()
    model = transformer.Transformer(cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = InferenceEngine(
        cfg,
        params,
        cfg=ServeConfig(
            max_slots=4,
            # Coarser floors than production defaults: 5 compiled
            # programs instead of 7 keeps the module fixture ~30%
            # cheaper, and bucket-coalescing behavior is
            # ladder-agnostic (the golden pins output independence).
            prefill_bucket_floor=16,
            kv_bucket_floor=32,
            max_queue=64,
            max_delay_s=0.002,
        ),
        registry=MetricsRegistry(),
    )
    counts = engine.warmup()
    assert sum(counts.values()) == engine.expected_compiles()
    yield engine
    assert engine.pool.active_slots == 0, "a test leaked KV slots"


def _mixed_requests(n, cfg, *, max_new=4, seed=123):
    """n mixed-length Requests spanning the prefill buckets, a third of
    them sampling (temperature/top_k) rather than greedy."""
    rng = np.random.default_rng(seed)
    cap = cfg.max_len - max_new
    reqs = []
    for i in range(n):
        ln = int(rng.integers(1, cap + 1)) if 0 < i < n - 1 else (1, cap)[
            i > 0
        ]
        temp, top_k = ((0.0, 0), (0.9, 0), (1.0, 7))[i % 3]
        reqs.append(
            Request(
                prompt=[int(t) for t in rng.integers(0, cfg.vocab_size, ln)],
                max_new_tokens=max_new,
                temperature=temp,
                top_k=top_k,
                seed=i,
            )
        )
    return reqs


# ------------------------------------------------------------------ units


class TestBuckets:
    def test_ladder_powers_of_two_capped(self):
        assert kv_cache.bucket_ladder(16, 100) == [16, 32, 64, 100]
        assert kv_cache.bucket_ladder(16, 64) == [16, 32, 64]
        assert kv_cache.bucket_ladder(64, 16) == [16]

    def test_ladder_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            kv_cache.bucket_ladder(0, 64)

    def test_pick_smallest_sufficient(self):
        ladder = [16, 32, 64]
        assert kv_cache.pick_bucket(ladder, 1) == 16
        assert kv_cache.pick_bucket(ladder, 16) == 16
        assert kv_cache.pick_bucket(ladder, 17) == 32
        assert kv_cache.pick_bucket(ladder, 64) == 64
        with pytest.raises(ValueError):
            kv_cache.pick_bucket(ladder, 65)


class TestKVCachePool:
    def _pool(self, slots=3, registry=None):
        return kv_cache.KVCachePool(
            num_layers=1, num_slots=slots, num_heads=2, max_len=8,
            head_dim=4, registry=registry or MetricsRegistry(),
        )

    def test_alloc_free_cycle(self):
        pool = self._pool()
        slots = [pool.alloc() for _ in range(3)]
        assert sorted(slots) == [0, 1, 2]
        assert pool.alloc() is None  # exhausted, not an exception
        pool.free(slots[1])
        assert pool.alloc() == slots[1]

    def test_double_free_raises(self):
        pool = self._pool()
        s = pool.alloc()
        pool.free(s)
        with pytest.raises(ValueError, match="already free"):
            pool.free(s)

    def test_occupancy_gauges_published(self):
        reg = MetricsRegistry()
        pool = self._pool(slots=4, registry=reg)
        pool.alloc()
        s = pool.alloc()
        pool.lengths[s] = 5
        pool.free(s)  # publish happens on transition
        g = reg.gauge_values()
        assert g["serving/kv_occupancy"] == 0.25
        assert g["serving/kv_slots_active"] == 1
        assert g["serving/kv_tokens"] == 0  # free() zeroed slot s

    def test_max_active_length_and_reset(self):
        pool = self._pool()
        a, b = pool.alloc(), pool.alloc()
        pool.lengths[a], pool.lengths[b] = 3, 7
        assert pool.max_active_length() == 7
        pool.reset()
        assert pool.max_active_length() == 0
        assert pool.active_slots == 0


class TestVarlenAttention:
    def test_matches_scalar_reference_per_slot(self):
        """Each slot must see exactly its own populated prefix — i.e.
        slot s of the vectorized op == the scalar-length reference run
        at length[s]."""
        import jax.numpy as jnp

        from tensorflow_examples_tpu.ops.decode import (
            decode_attention_reference,
        )

        rng = np.random.default_rng(0)
        S, H, K, D = 3, 2, 16, 4
        q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((S, H, K, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((S, H, K, D)), jnp.float32)
        lengths = jnp.asarray([1, 7, 16], jnp.int32)
        out = kv_cache.varlen_decode_attention(q, k, v, lengths)
        for s in range(S):
            ref = decode_attention_reference(
                q[s][None, :, None, :], k[s][None], v[s][None],
                int(lengths[s]),
            )
            np.testing.assert_allclose(
                np.asarray(out[s]), np.asarray(ref[0, :, 0, :]),
                rtol=1e-5, atol=1e-5,
            )


# ----------------------------------------------------------------- engine


class TestEngine:
    def test_failed_compiled_step_reallocates_caches(self, warm_engine):
        """The jitted steps donate the KV caches; a step that fails at
        runtime consumed them, so the engine must hand back fresh
        buffers (wrapped as EngineStepError) instead of serving 'Array
        has been deleted' forever after."""
        eng = warm_engine
        slot = eng.pool.alloc()
        tok, _ = eng.prefill(slot, [1, 2, 3])
        old_k = eng.pool.k
        orig = eng._decode_fns

        def boom(*a, **kw):
            raise RuntimeError("device lost")

        eng._decode_fns = {kb: boom for kb in orig}
        try:
            with pytest.raises(EngineStepError, match="decode step"):
                eng.decode([(slot, tok, 0, 0.0, 0)])
        finally:
            eng._decode_fns = orig
        eng.pool.free(slot)
        assert eng.pool.k is not old_k  # fresh zeroed buffers
        # ...and the engine serves again from the clean pool.
        slot = eng.pool.alloc()
        tok, _ = eng.prefill(slot, [1, 2, 3])
        out = eng.decode([(slot, tok, 0, 0.0, 0)])
        assert slot in out
        eng.pool.free(slot)

    @pytest.mark.timeout(120)
    def test_greedy_parity_with_flax_generate(self, warm_engine):
        """The serving forward (pure param-tree math, slot cache) and
        the flax decode path (Transformer.apply, scalar-index cache)
        are different implementations of the same model — greedy decode
        must agree token-for-token."""
        import jax

        eng = warm_engine
        prompt = [5, 190, 23, 41, 77, 8, 112]
        slot = eng.pool.alloc()
        tok, _ = eng.prefill(slot, prompt)
        served = [tok]
        for _ in range(5):
            served.append(eng.decode(
                [(slot, served[-1], 0, 0.0, 0)]
            )[slot])
        eng.pool.free(slot)

        model = transformer.Transformer(eng.model_cfg)
        out = transformer.generate(
            model, eng.params, np.asarray([prompt], np.int32),
            num_tokens=6, temperature=0.0, rng=jax.random.PRNGKey(0),
        )
        assert served == [int(t) for t in np.asarray(out)[0][len(prompt):]]

    def test_prompt_validation(self, warm_engine):
        with pytest.raises(ValueError, match="empty"):
            warm_engine.prefill(0, [])
        with pytest.raises(ValueError, match="exceeds max_len"):
            warm_engine.prefill(0, [1] * 65)

    def test_rejects_unsupported_models(self):
        with pytest.raises(NotImplementedError, match="dense"):
            InferenceEngine(tiny_cfg(moe_experts=4), {})
        with pytest.raises(ValueError, match="ring"):
            InferenceEngine(tiny_cfg(attention="ring"), {})

    def test_top_logprobs_normalized_and_ordered(self):
        logits = np.asarray([0.1, 3.0, -1.0, 2.0], np.float32)
        top = top_logprobs(logits, 3)
        assert [t["token"] for t in top] == [1, 3, 0]
        assert top[0]["logprob"] <= 0.0
        total = sum(np.exp(t["logprob"]) for t in top_logprobs(logits, 4))
        assert abs(total - 1.0) < 1e-6


# ----------------------------------------------- continuous-batching golden


class TestContinuousBatchingGolden:
    @pytest.mark.timeout(300)
    def test_batched_identical_to_unbatched_reference(self, warm_engine):
        """THE acceptance test: 20 concurrent mixed-length requests
        through the continuous batcher == 20 unbatched reference
        replays, bit for bit; exactly the warmed ladder's programs,
        zero post-warmup recompiles."""
        eng = warm_engine
        reqs = _mixed_requests(20, eng.model_cfg)
        compiles_before = dict(eng.sentinel.compile_counts())

        batcher = ContinuousBatcher(eng).start()
        try:
            futs = [batcher.submit(r) for r in reqs]
            results = [f.result(timeout=120) for f in futs]
        finally:
            batcher.close(drain=True)

        for req, res in zip(reqs, results):
            ref = eng.reference_generate(
                req.prompt, max_new=req.max_new_tokens, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            assert res.tokens == ref, (
                f"batched != reference for prompt_len={len(req.prompt)} "
                f"temp={req.temperature} top_k={req.top_k}"
            )
            assert res.truncated is None
            assert res.prompt_len == len(req.prompt)
            assert res.ttft_s is not None and res.total_s >= res.ttft_s

        assert eng.sentinel.compile_counts() == compiles_before, (
            "serving traffic after warmup must not compile anything new"
        )
        assert eng.post_warmup_recompiles() == 0
        assert eng.pool.active_slots == 0

    @pytest.mark.timeout(120)
    def test_eos_retires_early(self, warm_engine):
        """A request that hits its eos token frees the slot before
        max_new_tokens — the continuous part of continuous batching."""
        eng = warm_engine
        # Sampled stream so tokens vary; stop at the first repeat-free
        # token past index 0 (greedy references can emit runs).
        ref = eng.reference_generate(
            [9, 3, 5], max_new=6, seed=4, temperature=1.0
        )
        j = next(i for i, t in enumerate(ref) if i and t not in ref[:i])
        batcher = ContinuousBatcher(eng).start()
        try:
            res = batcher.submit(
                Request(prompt=[9, 3, 5], max_new_tokens=6, eos_id=ref[j],
                        temperature=1.0, seed=4)
            ).result(timeout=60)
        finally:
            batcher.close(drain=True)
        assert res.tokens == ref[:j + 1]
        assert res.truncated is None


# ----------------------------------------------------------- flow control


class _FakeEngine:
    """Deterministic, device-free engine stand-in so flow-control tests
    are O(ms) and can park the serve loop at will (``gate``)."""

    def __init__(self, *, max_slots=2, max_queue=2, max_len=32,
                 step_delay=0.0):
        self.cfg = ServeConfig(
            max_slots=max_slots, max_queue=max_queue, max_delay_s=0.0,
            request_timeout_s=5.0,
        )
        self.model_cfg = tiny_cfg(max_len=max_len)
        self.registry = MetricsRegistry()
        self.pool = kv_cache.KVCachePool(
            num_layers=1, num_slots=max_slots, num_heads=1, max_len=max_len,
            head_dim=2, registry=self.registry,
        )
        self.step_delay = step_delay
        self.gate = threading.Event()
        self.gate.set()
        self.warmed = True

    def post_warmup_recompiles(self):
        return 0

    def prefill(self, slot, prompt, *, seed=0, temperature=0.0, top_k=0):
        self.gate.wait(timeout=5)
        self.pool.lengths[slot] = len(prompt)
        last = np.zeros((self.model_cfg.vocab_size,), np.float32)
        last[prompt[-1] % self.model_cfg.vocab_size] = 1.0
        return (prompt[-1] + 1) % self.model_cfg.vocab_size, last

    def decode(self, entries):
        self.gate.wait(timeout=5)
        if self.step_delay:
            time.sleep(self.step_delay)
        out = {}
        for slot, token, _seed, _temp, _tk in entries:
            self.pool.lengths[slot] += 1
            out[slot] = (token + 1) % self.model_cfg.vocab_size
        return out


class TestBatcherFlowControl:
    def test_fake_engine_sequences(self):
        """The stand-in generates the arithmetic sequence the flow tests
        assert against."""
        eng = _FakeEngine()
        b = ContinuousBatcher(eng).start()
        try:
            res = b.submit(
                Request(prompt=[10], max_new_tokens=3)
            ).result(timeout=5)
        finally:
            b.close(drain=True)
        assert res.tokens == [11, 12, 13]

    def test_bounded_queue_sheds(self):
        """Queue at capacity -> QueueFull NOW (503), never unbounded
        growth; the shed is counted."""
        eng = _FakeEngine(max_queue=2)
        eng.gate.clear()  # park the loop so nothing drains
        b = ContinuousBatcher(eng)  # not started: queue only fills
        futs = [
            b.submit(Request(prompt=[1], max_new_tokens=1))
            for _ in range(2)
        ]
        with pytest.raises(QueueFull):
            b.submit(Request(prompt=[1], max_new_tokens=1))
        assert eng.registry.counter_values()["serving/shed_total"] == 1
        eng.gate.set()
        b.start()
        for f in futs:
            assert f.result(timeout=5).tokens == [2]
        b.close(drain=True)

    def test_draining_rejects_submit(self):
        eng = _FakeEngine()
        b = ContinuousBatcher(eng).start()
        b.close(drain=True)
        with pytest.raises(Draining):
            b.submit(Request(prompt=[1]))
        assert eng.registry.counter_values()["serving/rejected_total"] == 1

    def test_admission_rejects_over_budget(self):
        """prompt + generation budget > max_len fails the future fast —
        never touches a slot."""
        eng = _FakeEngine(max_len=8)
        b = ContinuousBatcher(eng)
        fut = b.submit(Request(prompt=[1] * 6, max_new_tokens=4))
        with pytest.raises(ValueError, match="must fit"):
            fut.result(timeout=1)
        fut = b.submit(Request(prompt=[1], kind="nonsense"))
        with pytest.raises(ValueError, match="unknown kind"):
            fut.result(timeout=1)
        assert eng.pool.active_slots == 0

    def test_queued_deadline_expires_without_device_work(self):
        eng = _FakeEngine()
        eng.gate.clear()
        b = ContinuousBatcher(eng)
        fut = b.submit(Request(prompt=[1], deadline_s=0.01))
        time.sleep(0.05)
        eng.gate.set()
        b.start()
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=5)
        b.close(drain=True)
        assert eng.registry.counter_values()["serving/expired_total"] == 1

    def test_zero_deadline_expires_not_unlimited(self):
        """deadline_s=0.0 is the STRICTEST deadline the API accepts —
        a falsy-zero check would silently flip it to 'no deadline'."""
        eng = _FakeEngine()
        b = ContinuousBatcher(eng).start()
        try:
            fut = b.submit(Request(prompt=[1], deadline_s=0.0))
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=5)
        finally:
            b.close(drain=True)

    def test_mid_generation_deadline_truncates(self):
        eng = _FakeEngine(max_len=64, step_delay=0.03)
        b = ContinuousBatcher(eng).start()
        try:
            res = b.submit(
                Request(prompt=[1], max_new_tokens=40, deadline_s=0.15)
            ).result(timeout=10)
        finally:
            b.close(drain=True)
        assert res.truncated == "deadline"
        assert 0 < len(res.tokens) < 40

    def test_engine_state_loss_fails_whole_active_batch(self):
        """An EngineStepError during prefill means the donated KV
        caches are gone — EVERY in-flight request must fail (its cache
        state no longer exists), not just the one being admitted."""
        eng = _FakeEngine(max_slots=2, max_len=64, step_delay=0.02)
        orig_prefill = eng.prefill
        calls = []

        def prefill(slot, prompt, **kw):
            calls.append(slot)
            if len(calls) == 2:
                raise EngineStepError("device lost; caches reallocated")
            return orig_prefill(slot, prompt, **kw)

        eng.prefill = prefill
        b = ContinuousBatcher(eng).start()
        try:
            fut_a = b.submit(Request(prompt=[1], max_new_tokens=40))
            time.sleep(0.1)  # A admitted, mid-generation
            fut_b = b.submit(Request(prompt=[2], max_new_tokens=2))
            with pytest.raises(EngineStepError):
                fut_b.result(timeout=5)
            with pytest.raises(EngineStepError):
                fut_a.result(timeout=5)
        finally:
            b.close(drain=False)
        assert eng.pool.active_slots == 0

    def test_drain_completes_request_staged_mid_prefill(self):
        """close(drain=True) arriving while the loop holds a dequeued
        request in prefill — queue empty, _active empty — must wait for
        it to finish, not declare the drain complete and truncate."""
        eng = _FakeEngine(max_len=32)
        eng.gate.clear()  # park the loop inside prefill
        b = ContinuousBatcher(eng).start()
        fut = b.submit(Request(prompt=[1], max_new_tokens=3))
        for _ in range(200):  # until the loop has dequeued it
            if b._staged:
                break
            time.sleep(0.005)
        assert b._staged == 1 and not b.queue_depth() and not b._active
        closer = threading.Thread(
            target=lambda: b.close(drain=True, timeout=10)
        )
        closer.start()
        time.sleep(0.05)  # drain poll is running, request still parked
        eng.gate.set()
        closer.join(timeout=10)
        res = fut.result(timeout=5)
        assert res.truncated is None and len(res.tokens) == 3

    def test_submit_racing_close_gets_draining(self):
        """A submit that passes the draining check just before close()
        sweeps the queue must still resolve — pulled back out and
        rejected, never left to block the caller's full timeout in a
        dead batcher."""
        eng = _FakeEngine()
        b = ContinuousBatcher(eng)  # never started
        orig_put = b._queues["interactive"].put_nowait

        def racing_put(item):  # close() lands between enqueue + recheck
            orig_put(item)
            b._draining = True
            b._stop.set()
            # The sweep takes the item and fails its future; submit's
            # recheck must defer to it rather than double-resolve.
            b._fail_pending(Draining("shut down"))

        b._queues["interactive"].put_nowait = racing_put
        fut = b.submit(Request(prompt=[1], max_new_tokens=1))
        with pytest.raises(Draining):
            fut.result(timeout=5)
        # And the variant where the sweep already ran BEFORE the
        # enqueue: submit itself must remove + reject.
        b2 = ContinuousBatcher(eng)
        orig_put2 = b2._queues["interactive"].put_nowait

        def racing_put2(item):
            orig_put2(item)
            b2._draining = True
            b2._stop.set()

        b2._queues["interactive"].put_nowait = racing_put2
        with pytest.raises(Draining):
            b2.submit(Request(prompt=[1], max_new_tokens=1))
        assert not b2.queue_depth()

    def test_close_without_drain_fails_queued(self):
        """A request still in the queue at shutdown gets Draining — a
        caller must never block forever on a dead batcher."""
        eng = _FakeEngine()
        b = ContinuousBatcher(eng)  # never started: stays queued
        fut = b.submit(Request(prompt=[1], max_new_tokens=1))
        b.close(drain=False)
        with pytest.raises(Draining):
            fut.result(timeout=5)

    def test_close_without_drain_retires_inflight_truncated(self):
        """An ADMITTED request at shutdown resolves with what it has,
        marked truncated="shutdown" (partial output over an error: the
        tokens already cost device time)."""
        eng = _FakeEngine(max_len=64, step_delay=0.05)
        b = ContinuousBatcher(eng).start()
        fut = b.submit(Request(prompt=[1], max_new_tokens=40))
        time.sleep(0.15)  # a few tokens in
        b.close(drain=False)
        res = fut.result(timeout=5)
        assert res.truncated == "shutdown"
        assert 0 < len(res.tokens) < 40

    def test_latency_histograms_recorded(self):
        eng = _FakeEngine()
        b = ContinuousBatcher(eng).start()
        try:
            b.submit(Request(prompt=[3], max_new_tokens=2)).result(timeout=5)
        finally:
            b.close(drain=True)
        hists = eng.registry.histogram_summaries()
        for name in ("queue_wait", "prefill", "ttft", "tpot", "e2e"):
            assert hists[f"serving/{name}"]["count"] >= 1, name

    def test_stats_line_is_valid_schema_v4(self):
        eng = _FakeEngine()
        b = ContinuousBatcher(eng).start()
        try:
            b.submit(Request(prompt=[3], max_new_tokens=1)).result(timeout=5)
            line = b.stats_line()
        finally:
            b.close(drain=True)
        assert line["kind"] == "serving"
        assert line["schema_version"] == schema.SERVING_SCHEMA_VERSION
        assert schema.validate_line(json.loads(json.dumps(line))) == []
        # v3 must NOT accept the serving kind or object.
        v3 = dict(line, schema_version=3)
        assert schema.validate_line(v3)
        # ...and a v1/v2 line smuggling the serving object is a
        # mislabeled v4 line, same rule as every earlier version bump.
        v2 = dict(line, schema_version=2, kind="window")
        del v2["host"]
        assert any(
            "v4 field 'serving'" in p for p in schema.validate_line(v2)
        )
        v1 = dict(v2, schema_version=1)
        assert any(
            "v4 field 'serving'" in p for p in schema.validate_line(v1)
        )
        # The serving object's documented-required keys are enforced.
        hollow = dict(line, serving={})
        assert any(
            "missing required key" in p
            for p in schema.validate_line(json.loads(json.dumps(hollow)))
        )


# --------------------------------------------------------------- frontend


@pytest.fixture(scope="module")
def live_frontend(warm_engine):
    """Module-scoped like warm_engine: the frontend tests only read or
    submit well-formed/rejected traffic, so one server serves them all
    (per-test start/close was ~0.5s of teardown each)."""
    batcher = ContinuousBatcher(warm_engine).start()
    frontend = ServingFrontend(batcher, port=0).start()
    yield frontend
    batcher.close(drain=True)
    frontend.close()


def _post(url, body, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class TestFrontend:
    @pytest.mark.timeout(120)
    def test_generate_over_http_matches_reference(self, live_frontend):
        eng = live_frontend.batcher.engine
        prompt = [17, 4, 99]
        status, reply = _post(
            live_frontend.url("/generate"),
            {"prompt": prompt, "max_new_tokens": 3, "seed": 5},
        )
        assert status == 200
        assert reply["tokens"] == eng.reference_generate(
            prompt, max_new=3, seed=5
        )
        assert reply["prompt_len"] == 3 and reply["truncated"] is None
        assert reply["ttft_s"] > 0 and reply["total_s"] >= reply["ttft_s"]

    @pytest.mark.timeout(120)
    def test_classify_over_http(self, live_frontend):
        eng = live_frontend.batcher.engine
        status, reply = _post(
            live_frontend.url("/classify"),
            {"prompt": [1, 2, 3], "top_n": 4},
        )
        assert status == 200
        assert reply["top"] == eng.reference_classify([1, 2, 3], top_n=4)

    def test_bad_requests_are_400(self, live_frontend):
        url = live_frontend.url("/generate")
        for body in (
            {},                                   # no prompt
            {"prompt": []},                       # empty
            {"prompt": [1.5]},                    # non-int ids
            {"prompt": [1], "bogus": 1},          # unknown field
            {"prompt": [1], "temperature": -1},   # out of range
            {"text": "hi"},                       # no tokenizer wired
            {"prompt": [1], "max_new_tokens": 1000},  # over budget
            {"prompt": [1], "max_new_tokens": None},  # explicit null
            {"prompt": [1], "temperature": None},     # explicit null
            {"prompt": [1], "seed": 2**31},           # > int32 seed
            {"prompt": [1], "top_k": 0.5},            # fractional int
            {"prompt": [999999]},                     # id >= vocab_size
            {"prompt": [-1]},                         # negative id
        ):
            status, reply = _post(url, body)
            assert status == 400, body
            assert "error" in reply
        # Bad JSON entirely.
        req = urllib.request.Request(
            url, data=b"{nope", headers={"Content-Type": "application/json"}
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400

    def test_bad_content_length_is_400(self, live_frontend):
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", live_frontend.port, timeout=10
        )
        try:
            conn.putrequest("POST", "/generate")
            conn.putheader("Content-Length", "abc")
            conn.endheaders()
            assert conn.getresponse().status == 400
        finally:
            conn.close()

    @pytest.mark.timeout(120)
    def test_metrics_health_window(self, live_frontend):
        _post(
            live_frontend.url("/generate"),
            {"prompt": [8, 9], "max_new_tokens": 2},
        )
        with urllib.request.urlopen(
            live_frontend.url("/metrics"), timeout=10
        ) as resp:
            text = resp.read().decode()
        for metric in (
            "serving_ttft_seconds", "serving_tpot_seconds",
            "serving_queue_wait_seconds", "serving_kv_occupancy",
            "serving_completed_total",
        ):
            assert metric in text, metric
        assert 'quantile="0.95"' in text

        with urllib.request.urlopen(
            live_frontend.url("/health"), timeout=10
        ) as resp:
            health = json.loads(resp.read())
        assert health["ok"] and not health["draining"]
        assert health["post_warmup_recompiles"] == 0

        with urllib.request.urlopen(
            live_frontend.url("/window"), timeout=10
        ) as resp:
            line = json.loads(resp.read())
        assert line["kind"] == "serving"
        assert schema.validate_line(line) == []

    def test_draining_maps_to_503(self):
        eng = _FakeEngine()
        b = ContinuousBatcher(eng).start()
        f = ServingFrontend(b, port=0)
        b.close(drain=True)
        status, reply = f.handle_request(
            {"prompt": [1]}, kind="generate"
        )
        assert status == 503 and reply["draining"]
        assert f.health_payload()[0] == 503

    def test_queue_full_maps_to_503(self):
        eng = _FakeEngine(max_queue=1)
        eng.gate.clear()
        b = ContinuousBatcher(eng)  # unstarted: queue fills
        f = ServingFrontend(b, port=0)
        b.submit(Request(prompt=[1]))
        status, reply = f.handle_request({"prompt": [1]}, kind="generate")
        assert status == 503 and reply.get("retry")
        eng.gate.set()
        b.start()
        b.close(drain=True)


# ------------------------------------------------------- paged KV (ISSUE 8)


def _tiny_params(cfg):
    import jax
    import jax.numpy as jnp

    model = transformer.Transformer(cfg)
    return model.init(
        {"params": jax.random.PRNGKey(1)}, jnp.zeros((1, 8), jnp.int32)
    )["params"]


@pytest.fixture(scope="module")
def paged_engine():
    """One warmed PAGED engine (fp32, block 8) for the module — same
    smoke model and ladder floors as ``warm_engine``, so every paged
    claim is measured against the exact dense baseline."""
    cfg = tiny_cfg()
    engine = InferenceEngine(
        cfg,
        _tiny_params(cfg),
        cfg=ServeConfig(
            max_slots=4,
            prefill_bucket_floor=16,
            kv_bucket_floor=32,
            max_queue=64,
            max_delay_s=0.002,
            kv_block_size=8,
        ),
        registry=MetricsRegistry(),
    )
    counts = engine.warmup()
    assert sum(counts.values()) == engine.expected_compiles()
    yield engine
    assert engine.pool.active_slots == 0, "a test leaked KV slots"


class TestPagedPool:
    def _pool(self, *, slots=3, blocks=0, block=8, registry=None, **kw):
        return PagedKVPool(
            num_layers=1, num_slots=slots, num_heads=2, max_len=64,
            head_dim=4, block_size=block, num_blocks=blocks,
            registry=registry or MetricsRegistry(), **kw,
        )

    def test_block_size_must_divide_max_len(self):
        with pytest.raises(ValueError, match="power of two"):
            self._pool(block=12)
        with pytest.raises(ValueError, match="divide max_len"):
            PagedKVPool(
                num_layers=1, num_slots=2, num_heads=2, max_len=60,
                head_dim=4, block_size=8, registry=MetricsRegistry(),
            )

    def test_alloc_assign_free_returns_blocks(self):
        pool = self._pool()
        slot = pool.alloc()
        blocks = pool.alloc_blocks(3)
        assert paged_kv.NULL_BLOCK not in blocks
        pool.assign(slot, blocks)
        assert pool.used_bytes() == 3 * pool.bytes_per_block()
        pool.free(slot)
        assert pool.used_bytes() == 0
        # Freed blocks are reusable immediately (free-list reuse).
        slot2 = pool.alloc()
        blocks2 = pool.alloc_blocks(3)
        assert set(blocks2) <= set(blocks)
        pool.assign(slot2, blocks2)
        pool.free(slot2)

    def test_exhaustion_is_loud_and_all_or_nothing(self):
        reg = MetricsRegistry()
        pool = self._pool(blocks=4, registry=reg)  # 3 usable
        slot = pool.alloc()
        pool.assign(slot, pool.alloc_blocks(2))
        with pytest.raises(BlockExhausted, match="exhausted"):
            pool.alloc_blocks(2)  # only 1 left: claim nothing
        assert reg.counter_values()["serving/kv_exhausted_total"] == 1
        # The failed claim leaked nothing: the single block remains.
        assert len(pool.alloc_blocks(1)) == 1
        pool.free(slot)

    def test_ensure_position_grows_one_block(self):
        pool = self._pool(blocks=4)
        slot = pool.alloc()
        pool.assign(slot, pool.alloc_blocks(1))
        pool.ensure_position(slot, 7)   # still inside block 0
        assert pool.paged_stats()["blocks_used"] == 1
        pool.ensure_position(slot, 8)   # crosses into block 1
        assert pool.paged_stats()["blocks_used"] == 2
        pool.free(slot)

    def test_occupancy_gauge_split(self):
        """THE satellite fix: every slot claimed on short prompts must
        NOT read as a full pool — kv_occupancy is used-block fraction,
        slot occupancy is published separately."""
        reg = MetricsRegistry()
        pool = self._pool(slots=2, blocks=17, registry=reg)  # 16 usable
        for _ in range(2):
            s = pool.alloc()
            pool.assign(s, pool.alloc_blocks(1))  # 8-token request
        g = reg.gauge_values()
        assert g["serving/kv_slot_occupancy"] == 1.0
        assert g["serving/kv_occupancy"] == pytest.approx(2 / 16)
        assert pool.occupancy == pytest.approx(2 / 16)
        for s in range(2):
            pool.free(s)

    def test_prefix_cache_hit_miss_and_partial_tail(self):
        pool = self._pool(slots=3, blocks=33)
        prompt = list(range(20))  # blocks [0:8), [8:16), partial tail
        blocks, c = pool.prefix_lookup(prompt)
        assert (blocks, c) == ([], 0) and pool.prefix_misses == 1
        slot = pool.alloc()
        pool.assign(slot, pool.alloc_blocks(3))
        pool.insert_prefix(slot, prompt)
        # Same full-block prefix, different tail: 2-block hit.
        hit_blocks, c = pool.prefix_lookup(list(range(16)) + [99, 98])
        assert c == 16 and len(hit_blocks) == 2
        assert hit_blocks == list(pool.block_tables[slot, :2])
        pool.release_prefix(hit_blocks)
        # A prompt that IS exactly the cached blocks caps at n-1: at
        # least one tail token must prefill to sample from.
        hb, c = pool.prefix_lookup(list(range(16)))
        assert c == 8 and len(hb) == 1
        pool.release_prefix(hb)
        # Diverging first block: miss.
        assert pool.prefix_lookup([7] * 16) == ([], 0)
        # The partial tail block (tokens 16..19) was never published.
        assert len(pool._cache) == 2
        pool.free(slot)

    def test_shared_blocks_survive_owner_free_then_evict(self):
        """COW discipline: a published block outlives its owner (parked
        evictable, still hittable), is never handed out while
        referenced, and is reclaimed under pressure."""
        pool = self._pool(slots=3, blocks=5)  # 4 usable
        prompt = list(range(8))
        a = pool.alloc()
        pool.assign(a, pool.alloc_blocks(1))
        pool.insert_prefix(a, prompt)
        shared = int(pool.block_tables[a, 0])
        pool.free(a)  # refcount 0 but published: parked, NOT free
        hb, c = pool.prefix_lookup(prompt + [50])
        assert hb == [shared] and c == 8
        # While referenced, an allocation storm cannot reclaim it.
        got = pool.alloc_blocks(3)
        assert shared not in got
        with pytest.raises(BlockExhausted):
            pool.alloc_blocks(1)
        pool.release_prefix(hb)
        for b in got:
            pool._refcount[b] = 0  # simulate frees
            pool._free_blocks.append(b)
        # Unreferenced now: pressure evicts it out of the cache.
        got2 = pool.alloc_blocks(4)
        assert shared in got2
        assert pool.prefix_lookup(prompt + [50]) == ([], 0)

    def test_reset_after_eviction_has_no_duplicate_free_blocks(self):
        """Regression: reset() used to rebuild the free list and THEN
        return parked evictable blocks onto it — the same physical
        block id twice, i.e. two requests silently sharing (and
        overwriting) one block."""
        pool = self._pool(slots=2, blocks=5)
        s = pool.alloc()
        pool.assign(s, pool.alloc_blocks(1))
        pool.insert_prefix(s, list(range(8)))
        pool.free(s)  # published + unreferenced: parked evictable
        pool.reset()
        assert sorted(pool._free_blocks) == [1, 2, 3, 4]  # no dupes
        s = pool.alloc()
        got = pool.alloc_blocks(4)
        assert len(set(got)) == 4
        pool.assign(s, got)
        pool.free(s)

    def test_memory_claim_mixed_lengths_half_of_dense(self):
        """Acceptance: a mixed short/long request set commits <= 1/2 of
        the dense pool's bytes at equal concurrency, by the pools' own
        byte accounting."""
        lengths = [4, 8, 12, 4, 60, 8, 4, 8]
        dense = kv_cache.KVCachePool(
            num_layers=2, num_slots=8, num_heads=2, max_len=64,
            head_dim=16, registry=MetricsRegistry(),
        )
        paged = PagedKVPool(
            num_layers=2, num_slots=8, num_heads=2, max_len=64,
            head_dim=16, block_size=8, registry=MetricsRegistry(),
        )
        for ln in lengths:
            ds = dense.alloc()
            dense.lengths[ds] = ln
            ps = paged.alloc()
            paged.assign(ps, paged.alloc_blocks(-(-ln // 8)))
            paged.lengths[ps] = ln
        assert dense.active_slots == paged.active_slots == 8
        assert paged.used_bytes() <= dense.used_bytes() / 2, (
            f"paged {paged.used_bytes()} vs dense {dense.used_bytes()}"
        )
        for s in range(8):
            dense.free(s)
            paged.free(s)


class TestPagedGolden:
    @pytest.mark.timeout(300)
    def test_batched_identical_to_unbatched_reference(self, paged_engine):
        """Acceptance: the PR 5 concurrent-request batcher golden on
        the PAGED pool — 12 mixed-length requests through the
        continuous batcher, token-identical to the unbatched reference
        replay, zero post-warmup recompiles via the sentinel."""
        eng = paged_engine
        reqs = _mixed_requests(12, eng.model_cfg)
        compiles_before = dict(eng.sentinel.compile_counts())

        batcher = ContinuousBatcher(eng).start()
        try:
            futs = [batcher.submit(r) for r in reqs]
            results = [f.result(timeout=120) for f in futs]
        finally:
            batcher.close(drain=True)

        for req, res in zip(reqs, results):
            ref = eng.reference_generate(
                req.prompt, max_new=req.max_new_tokens, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            assert res.tokens == ref, (
                f"paged batched != reference for "
                f"prompt_len={len(req.prompt)} temp={req.temperature}"
            )
            assert res.truncated is None
        assert eng.sentinel.compile_counts() == compiles_before
        assert eng.post_warmup_recompiles() == 0
        assert eng.pool.active_slots == 0
        assert eng.pool.used_bytes() == 0  # every block returned

    @pytest.mark.timeout(120)
    def test_prefix_hit_extends_token_identical_and_cow(self, paged_engine):
        """A prefix-cache hit must change nothing observable: request B
        reusing A's cached blocks serves the exact reference tokens
        (the extend program's chunked attention), and A's published
        blocks are bit-identical after B ran (copy-on-write: shared
        full blocks are never written)."""
        import numpy as np

        eng = paged_engine
        rng = np.random.default_rng(11)
        prefix = [int(t) for t in rng.integers(0, 211, 16)]
        a_req = Request(prompt=prefix + [3, 1, 4], max_new_tokens=3,
                        seed=21)
        b_req = Request(prompt=prefix + [9, 2, 6, 5], max_new_tokens=4,
                        seed=22, temperature=0.9)
        hits_before = eng.pool.prefix_hits
        batcher = ContinuousBatcher(eng).start()
        try:
            res_a = batcher.submit(a_req).result(timeout=60)
            # A retired; its full prefix blocks stay published.
            shared = [
                bid for bid, key in eng.pool._cache_key.items()
                if list(key[1]) == prefix[:8] or list(key[1]) == prefix[8:]
            ]
            assert len(shared) == 2
            k_before = np.asarray(eng.pool.k[:, shared]).copy()
            res_b = batcher.submit(b_req).result(timeout=60)
        finally:
            batcher.close(drain=True)
        assert eng.pool.prefix_hits == hits_before + 1
        assert res_a.tokens == eng.reference_generate(
            a_req.prompt, max_new=3, seed=21
        )
        assert res_b.tokens == eng.reference_generate(
            b_req.prompt, max_new=4, seed=22, temperature=0.9
        )
        np.testing.assert_array_equal(
            np.asarray(eng.pool.k[:, shared]), k_before,
            err_msg="a shared prefix block was written (COW violated)",
        )
        assert eng.post_warmup_recompiles() == 0


class TestPagedFlashGolden:
    """ISSUE 11: the fused Pallas paged-decode kernel
    (``attention="paged_flash"``, ops/paged_decode.py) behind the SAME
    batcher golden the gather path passes — the kernel is a launch/HBM
    optimization, never a numerics change."""

    @pytest.mark.timeout(300)
    def test_paged_batcher_golden_under_fused_kernel(self):
        cfg = tiny_cfg()
        eng = InferenceEngine(
            cfg,
            _tiny_params(cfg),
            cfg=ServeConfig(
                max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
                max_delay_s=0.002, kv_block_size=8,
                attention="paged_flash",
            ),
            registry=MetricsRegistry(),
        )
        counts = eng.warmup()
        assert sum(counts.values()) == eng.expected_compiles()
        reqs = _mixed_requests(8, eng.model_cfg)
        batcher = ContinuousBatcher(eng).start()
        try:
            futs = [batcher.submit(r) for r in reqs]
            results = [f.result(timeout=120) for f in futs]
        finally:
            batcher.close(drain=True)
        for req, res in zip(reqs, results):
            ref = eng.reference_generate(
                req.prompt, max_new=req.max_new_tokens, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            assert res.tokens == ref, (
                f"paged_flash != reference for prompt_len="
                f"{len(req.prompt)} temp={req.temperature}"
            )
        assert eng.post_warmup_recompiles() == 0
        assert eng.pool.active_slots == 0

    @pytest.mark.timeout(240)
    def test_int8_dequant_in_kernel_tracks_fp32(self):
        """int8 KV under the fused kernel: same bounded-divergence
        contract as the gather path (first token exact — prefill
        attends fresh unquantized K/V — and >= 75% stream agreement)."""
        import numpy as np

        cfg = tiny_cfg(num_layers=1, d_model=16, max_len=32)
        eng = InferenceEngine(
            cfg,
            _tiny_params(cfg),
            cfg=ServeConfig(
                max_slots=2, prefill_bucket_floor=16, kv_bucket_floor=16,
                kv_block_size=8, kv_dtype="int8",
                attention="paged_flash",
            ),
            registry=MetricsRegistry(),
        )
        eng.warmup()
        rng = np.random.default_rng(5)
        for i in range(2):
            prompt = [int(t) for t in rng.integers(0, 211, 5 + i * 6)]
            slot = eng.pool.alloc()
            tok, _ = eng.prefill(slot, prompt, seed=i)
            seq = [tok]
            for _ in range(5):
                seq.append(eng.decode([(slot, seq[-1], i, 0.0, 0)])[slot])
            eng.pool.free(slot)
            ref = eng.reference_generate(prompt, max_new=6, seed=i)
            assert seq[0] == ref[0], "first token must be exact"
            agree = sum(a == b for a, b in zip(seq, ref))
            assert agree >= 0.75 * len(ref), (
                f"int8 paged_flash diverged beyond bound: {seq} vs {ref}"
            )
        assert eng.post_warmup_recompiles() == 0


class TestPagedExhaustionServing:
    @pytest.mark.timeout(120)
    def test_mid_decode_exhaustion_fails_loudly_engine_keeps_serving(self):
        """Satellite: block exhaustion mid-decode fails THAT request
        with BlockExhausted (no device state was lost — no donation
        happened), its blocks return to the free list, and the engine
        keeps serving new requests — mirroring the PR 5
        EngineStepError contract without the blast radius."""
        cfg = tiny_cfg()
        eng = InferenceEngine(
            cfg,
            _tiny_params(cfg),
            cfg=ServeConfig(
                max_slots=2, prefill_bucket_floor=16, kv_bucket_floor=32,
                max_delay_s=0.0, kv_block_size=8,
                kv_blocks=4,  # 3 usable blocks = 24 token rows
            ),
            registry=MetricsRegistry(),
        )
        eng.warmup()
        batcher = ContinuousBatcher(eng).start()
        try:
            # 16-token prompt (2 blocks) + enough generation to need a
            # 4th block the pool cannot back.
            doomed = batcher.submit(
                Request(prompt=list(range(100, 116)),
                        max_new_tokens=20, seed=1)
            )
            with pytest.raises(BlockExhausted, match="exhausted"):
                doomed.result(timeout=60)
            assert eng.pool.used_bytes() == 0  # blocks came back
            # The engine serves the next request cleanly.
            ok = batcher.submit(
                Request(prompt=[5, 6, 7], max_new_tokens=3, seed=2)
            ).result(timeout=60)
        finally:
            batcher.close(drain=True)
        assert ok.tokens == eng.reference_generate(
            [5, 6, 7], max_new=3, seed=2
        )
        assert eng.post_warmup_recompiles() == 0
        assert (
            eng.registry.counter_values()["serving/kv_exhausted_total"]
            >= 1
        )


class TestInt8KV:
    @pytest.mark.timeout(180)
    def test_bounded_divergence_vs_fp32_reference(self):
        """The int8 golden: quantized-KV generation tracks the fp32
        reference within a measured bound — first generated token
        exact (prefill attends over fresh unquantized K/V), and >= 75%
        of each stream agreeing — with zero post-warmup recompiles.
        Divergence is bounded and measured, never assumed away."""
        import numpy as np

        cfg = tiny_cfg(num_layers=1, d_model=16, max_len=32)
        eng = InferenceEngine(
            cfg,
            _tiny_params(cfg),
            cfg=ServeConfig(
                max_slots=2, prefill_bucket_floor=16, kv_bucket_floor=16,
                kv_block_size=8, kv_dtype="int8",
            ),
            registry=MetricsRegistry(),
        )
        eng.warmup()
        assert eng.pool.kv_bits == 8
        rng = np.random.default_rng(5)
        for i in range(4):
            prompt = [int(t) for t in rng.integers(0, 211, 5 + i * 6)]
            slot = eng.pool.alloc()
            tok, _ = eng.prefill(slot, prompt, seed=i)
            seq = [tok]
            for _ in range(5):
                seq.append(eng.decode([(slot, seq[-1], i, 0.0, 0)])[slot])
            eng.pool.free(slot)
            ref = eng.reference_generate(prompt, max_new=6, seed=i)
            assert seq[0] == ref[0], "first token must be exact"
            agree = sum(a == b for a, b in zip(seq, ref))
            assert agree >= 0.75 * len(ref), (
                f"int8 diverged beyond bound: {seq} vs {ref}"
            )
        assert eng.post_warmup_recompiles() == 0

    def test_int8_requires_paged_pool(self):
        cfg = tiny_cfg()
        with pytest.raises(ValueError, match="paged"):
            InferenceEngine(
                cfg, _tiny_params(cfg),
                cfg=ServeConfig(kv_dtype="int8"),
                registry=MetricsRegistry(),
            )


class TestFp8KV:
    """fp8 KV (ISSUE 15): falls out of the precision registry — the
    int8 write/gather/wire paths are dtype-generic, the pool just
    stores float8_e4m3fn."""

    @pytest.mark.timeout(180)
    def test_bounded_divergence_vs_fp32_reference(self):
        from tensorflow_examples_tpu.core import precision

        if not precision.fp8_supported():
            pytest.skip("no working float8_e4m3fn on this build")
        cfg = tiny_cfg(num_layers=1, d_model=16, max_len=32)
        eng = InferenceEngine(
            cfg,
            _tiny_params(cfg),
            cfg=ServeConfig(
                max_slots=2, prefill_bucket_floor=16, kv_bucket_floor=16,
                kv_block_size=8, kv_dtype="fp8",
            ),
            registry=MetricsRegistry(),
        )
        eng.warmup()
        assert eng.pool.kv_bits == 8
        assert eng.pool.k.dtype == precision.fp8_dtype()
        rng = np.random.default_rng(5)
        for i in range(3):
            prompt = [int(t) for t in rng.integers(0, 211, 5 + i * 6)]
            slot = eng.pool.alloc()
            tok, _ = eng.prefill(slot, prompt, seed=i)
            seq = [tok]
            for _ in range(5):
                seq.append(eng.decode([(slot, seq[-1], i, 0.0, 0)])[slot])
            eng.pool.free(slot)
            ref = eng.reference_generate(prompt, max_new=6, seed=i)
            assert seq[0] == ref[0], "first token must be exact"
            agree = sum(a == b for a, b in zip(seq, ref))
            assert agree >= 0.75 * len(ref), (
                f"fp8 diverged beyond bound: {seq} vs {ref}"
            )
        assert eng.post_warmup_recompiles() == 0

    def test_fp8_rejects_fused_kernel(self):
        from tensorflow_examples_tpu.core import precision

        if not precision.fp8_supported():
            pytest.skip("no working float8_e4m3fn on this build")
        cfg = tiny_cfg(num_layers=1, d_model=16, max_len=32)
        with pytest.raises(ValueError, match="paged_flash"):
            InferenceEngine(
                cfg, _tiny_params(cfg),
                cfg=ServeConfig(
                    kv_block_size=8, kv_dtype="fp8",
                    attention="paged_flash",
                    prefill_bucket_floor=16, kv_bucket_floor=16,
                ),
                registry=MetricsRegistry(),
            )


class TestQuantizedWeights:
    """Weight-only quantization (ISSUE 15 tentpole): the registry
    rewrites the tree at load time, the forward dequantizes in the
    matmuls, and serving stays exactly as deterministic as the tree
    it was given."""

    def _engines(self, weight_dtype):
        cfg = tiny_cfg()
        params = _tiny_params(cfg)
        kw = dict(
            max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
        )
        f32 = InferenceEngine(
            cfg, params, cfg=ServeConfig(**kw),
            registry=MetricsRegistry(),
        )
        quant = InferenceEngine(
            cfg, params,
            cfg=ServeConfig(weight_dtype=weight_dtype, **kw),
            registry=MetricsRegistry(),
        )
        return f32, quant

    @pytest.mark.timeout(300)
    def test_batcher_golden_bounded_divergence_vs_f32(self):
        """THE quantized acceptance: int8-weight serving through the
        continuous batcher is (a) token-identical to its OWN unbatched
        reference — batching never changes numerics, quantized or not
        — and (b) first-token-exact with >= 75% stream agreement
        against the f32 engine, with zero post-warmup recompiles and
        HBM param bytes <= 0.35x f32 (engine.byte_breakdown)."""
        f32, quant = self._engines("int8")
        assert quant.quantized_weights and not f32.quantized_weights
        bb_q, bb_f = quant.byte_breakdown(), f32.byte_breakdown()
        assert bb_q["weight_bits"] == 8
        assert bb_q["params_bytes"] <= 0.35 * bb_f["params_bytes"], (
            f"{bb_q['params_bytes']} vs f32 {bb_f['params_bytes']}"
        )
        quant.warmup()
        reqs = _mixed_requests(10, quant.model_cfg)
        batcher = ContinuousBatcher(quant).start()
        try:
            results = [
                f.result(timeout=120)
                for f in [batcher.submit(r) for r in reqs]
            ]
        finally:
            batcher.close(drain=True)
        first_exact = 0
        for req, res in zip(reqs, results):
            own_ref = quant.reference_generate(
                req.prompt, max_new=req.max_new_tokens, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            assert res.tokens == own_ref, (
                "quantized batching must stay token-identical to the "
                "quantized reference"
            )
            f32_ref = f32.reference_generate(
                req.prompt, max_new=req.max_new_tokens, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            first_exact += res.tokens[0] == f32_ref[0]
            agree = sum(a == b for a, b in zip(res.tokens, f32_ref))
            assert agree >= 0.75 * len(f32_ref), (
                f"int8 weights diverged beyond bound: {res.tokens} vs "
                f"{f32_ref}"
            )
        assert first_exact == len(reqs), "first tokens must be exact"
        assert quant.post_warmup_recompiles() == 0

    @pytest.mark.timeout(180)
    def test_fp8_weights_bounded_divergence(self):
        from tensorflow_examples_tpu.core import precision

        if not precision.fp8_supported():
            pytest.skip("no working float8_e4m3fn on this build")
        f32, quant = self._engines("fp8")
        assert quant.byte_breakdown()["weight_bits"] == 8
        quant.warmup()
        rng = np.random.default_rng(3)
        for i in range(3):
            prompt = [int(t) for t in rng.integers(0, 200, 4 + 9 * i)]
            got = quant.reference_generate(prompt, max_new=6, seed=i)
            ref = f32.reference_generate(prompt, max_new=6, seed=i)
            assert got[0] == ref[0]
            agree = sum(a == b for a, b in zip(got, ref))
            assert agree >= 0.75 * len(ref)
        assert quant.post_warmup_recompiles() == 0

    @pytest.mark.timeout(180)
    def test_quantized_paged_prefix_and_spec_compose(self):
        """The registry composes with the rest of the serving stack:
        paged pool + prefix cache + speculation, all on, quantized
        tree — batched streams still token-identical to the quantized
        reference, zero recompiles."""
        cfg = tiny_cfg()
        eng = InferenceEngine(
            cfg, _tiny_params(cfg),
            cfg=ServeConfig(
                max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
                weight_dtype="int8", kv_block_size=8, kv_dtype="int8",
                spec_decode_k=3,
            ),
            registry=MetricsRegistry(),
        )
        eng.warmup()
        reqs = _mixed_requests(6, cfg)
        batcher = ContinuousBatcher(eng).start()
        try:
            results = [
                f.result(timeout=120)
                for f in [batcher.submit(r) for r in reqs]
            ]
        finally:
            batcher.close(drain=True)
        for req, res in zip(reqs, results):
            ref = eng.reference_generate(
                req.prompt, max_new=req.max_new_tokens, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            assert res.tokens == ref
        assert eng.post_warmup_recompiles() == 0

    def test_cast_only_precision_config_applies(self):
        """A registry with cast rules and no quantization still runs
        at load time: precision=PrecisionConfig(default='bf16') serves
        bf16 leaves, never a silently-f32 tree."""
        import jax.numpy as jnp

        from tensorflow_examples_tpu.core.precision import PrecisionConfig

        cfg = tiny_cfg()
        eng = InferenceEngine(
            cfg, _tiny_params(cfg),
            cfg=ServeConfig(
                max_slots=2, prefill_bucket_floor=16, kv_bucket_floor=32,
            ),
            registry=MetricsRegistry(),
            precision=PrecisionConfig(default="bf16"),
        )
        assert eng.params["wte"]["embedding"].dtype == jnp.bfloat16
        assert eng.params["h_0"]["ln_1"]["scale"].dtype == jnp.bfloat16
        assert not eng.quantized_weights

    def test_v11_keys_stamped_only_when_quantized(self):
        """The schema-v11 serving keys ride the stats line exactly when
        the engine serves quantized weights (optional-on-write, like
        every bump); the line validates either way."""
        _, quant = self._engines("int8")
        quant.warmup()
        b = ContinuousBatcher(quant).start()
        try:
            line = b.stats_line()
        finally:
            b.close(drain=True)
        assert schema.validate_line(line) == []
        assert line["schema_version"] == schema.SERVING_SCHEMA_VERSION
        for key in schema.SERVING_KEYS_V11:
            assert key in line["serving"], key
        assert line["serving"]["weight_bits"] == 8
        assert line["serving"]["quantized_params"] > 0
        assert (
            line["serving"]["param_bytes"]
            < line["serving"]["param_bytes_f32"]
        )

    def test_v11_keys_absent_on_unquantized_line(self, warm_engine):
        b = ContinuousBatcher(warm_engine).start()
        try:
            line = b.stats_line()
        finally:
            b.close(drain=True)
        assert schema.validate_line(line) == []
        for key in schema.SERVING_KEYS_V11:
            assert key not in line["serving"], key

    def test_v11_keys_flagged_on_older_versions(self):
        """Mislabeling rule: a v10 line carrying a v11 key is flagged,
        like every earlier bump."""
        _, quant = self._engines("int8")
        quant.warmup()
        b = ContinuousBatcher(quant).start()
        try:
            line = b.stats_line()
        finally:
            b.close(drain=True)
        line["schema_version"] = 10
        problems = schema.validate_line(line)
        assert any("v11 serving key" in p for p in problems)


# ------------------------------------------------------------ SIGTERM drain


class _FakeGuard:
    requested = False

    def install(self):
        return self

    def uninstall(self):
        pass


class TestPreemptionDrain:
    @pytest.mark.timeout(60)
    def test_drain_finishes_inflight_rejects_new(self):
        """run_until_preempted: signal -> in-flight requests complete,
        new ones are 503, returns 0."""
        eng = _FakeEngine(max_slots=2, max_queue=8, step_delay=0.02)
        batcher = ContinuousBatcher(eng).start()
        frontend = ServingFrontend(batcher, port=0)
        guard = _FakeGuard()
        rc = [None]
        t = threading.Thread(
            target=lambda: rc.__setitem__(
                0, run_until_preempted(frontend, poll_s=0.01, guard=guard)
            )
        )
        t.start()
        futs = [
            batcher.submit(Request(prompt=[i], max_new_tokens=20))
            for i in range(4)
        ]
        time.sleep(0.05)  # some tokens in flight
        guard.requested = True
        t.join(timeout=30)
        assert rc[0] == 0
        for i, f in enumerate(futs):
            assert f.result(timeout=1).tokens == [
                (i + k + 1) % eng.model_cfg.vocab_size for k in range(20)
            ]
        with pytest.raises(Draining):
            batcher.submit(Request(prompt=[1]))
        assert (
            eng.registry.counter_values()["serving/preemptions"] == 1
        )

    @pytest.mark.faults
    @pytest.mark.slow
    @pytest.mark.timeout(240)
    def test_sigterm_subprocess_drains_and_exits_zero(self, tmp_path):
        """Real-signal parity check: SIGTERM to a serving process over
        real sockets drains and exits 0 (the training preemption
        contract, resilience-layer parity). Marked slow like the
        watchdog fail-fast subprocess check and for the same reason:
        the mechanism (run_until_preempted drain/503/rc-0) is already
        unit-covered in tier-1 just above; this out-of-band run pays a
        full fresh-interpreter jax import to add only the real-signal
        delivery."""
        script = tmp_path / "serve_victim.py"
        script.write_text(
            f"""
import json, os, sys, threading
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {REPO!r})
sys.path.insert(0, os.path.join({REPO!r}, "tools"))
import serve_bench
from tensorflow_examples_tpu.serving.batcher import (
    ContinuousBatcher, Request,
)
from tensorflow_examples_tpu.serving.frontend import (
    ServingFrontend, run_until_preempted,
)
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

# No warmup(): the drain contract is what's under test, and lazy
# first-compiles are within the sentinel allowance (recompiles stays
# 0); the warmed-ladder contract is the serve_bench smoke's job.
engine = serve_bench.build_smoke_engine(registry=MetricsRegistry())
batcher = ContinuousBatcher(engine).start()
frontend = ServingFrontend(batcher, port=0).start()

# Long-running traffic so SIGTERM lands mid-generation.
futs = [
    batcher.submit(Request(prompt=[i + 1], max_new_tokens=40, seed=i))
    for i in range(4)
]
print(json.dumps({{"ready": True, "port": frontend.port}}), flush=True)
rc = run_until_preempted(frontend, poll_s=0.02)
done = sum(1 for f in futs if f.done() and not f.exception())
print(json.dumps({{"rc": rc, "completed": done,
                  "recompiles": engine.post_warmup_recompiles()}}),
      flush=True)
sys.exit(rc)
"""
        )
        proc = subprocess.Popen(
            [sys.executable, str(script)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            ready = json.loads(proc.stdout.readline())
            assert ready["ready"]
            time.sleep(0.3)  # let some decode steps run
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=180)
        except Exception:
            proc.kill()
            raise
        assert proc.returncode == 0, err[-2000:]
        final = json.loads(out.strip().splitlines()[-1])
        assert final["rc"] == 0
        assert final["completed"] == 4, "drain must finish in-flight work"
        assert final["recompiles"] == 0
