"""Overload tier (ISSUE 13): load is a normal input.

The load-bearing tests:

* :class:`TestFlashCrowdGolden` — a seeded 3x flash crowd through a
  2-replica fleet (device-free engines, real batcher/frontend/router/
  HTTP): ALL shedding lands on the batch class, every interactive
  request completes with a token-identical stream, the brownout ladder
  engages and fully clears within the run.
* :class:`TestSloAdmission` / :class:`TestPreemption` — interactive is
  admitted first and PREEMPTS batch for decode slots, with the
  preempted batch request replayed token-identically.
* :class:`TestOverloadController` — the brownout ladder's state
  machine under a fake clock: one rung per hold on the way up,
  sustained-clear hysteresis on the way down, per-level enforcement.
* :class:`TestSchemaV10` — the schema bump pins: per-class p95s, shed
  counters, brownout level/transitions, digest_truncated — forbidden
  on v4-v9 serving lines like every earlier bump.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from tensorflow_examples_tpu.serving import kv_cache
from tensorflow_examples_tpu.serving.batcher import (
    ContinuousBatcher,
    QueueFull,
    Request,
)
from tensorflow_examples_tpu.serving.engine import ServeConfig
from tensorflow_examples_tpu.serving.frontend import ServingFrontend
from tensorflow_examples_tpu.serving.overload import (
    LEVEL_CAP_TOKENS,
    LEVEL_NO_SPEC,
    LEVEL_SHED_BATCH,
    LEVEL_SHED_INTERACTIVE,
    MAX_LEVEL,
    OverloadController,
)
from tensorflow_examples_tpu.serving.router import (
    Router,
    RouterConfig,
    RouterFrontend,
)
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _FakeEngine:
    """Deterministic device-free engine (test_router's, plus
    ServeConfig passthrough so tests can turn the brownout knobs):
    token stream is prompt[-1]+1, +2, ... so replay after preemption
    or failover cannot change results."""

    def __init__(self, *, max_slots=4, max_queue=32, max_len=64,
                 step_delay=0.0, **cfg_kw):
        self.cfg = ServeConfig(
            max_slots=max_slots, max_queue=max_queue, max_delay_s=0.0,
            request_timeout_s=30.0, **cfg_kw,
        )
        import serve_bench

        from tensorflow_examples_tpu.models import transformer

        base = dict(serve_bench.SMOKE_MODEL)
        base["max_len"] = max_len
        self.model_cfg = transformer.TransformerConfig(**base)
        self.registry = MetricsRegistry()
        self.pool = kv_cache.KVCachePool(
            num_layers=1, num_slots=max_slots, num_heads=1,
            max_len=max_len, head_dim=2, registry=self.registry,
        )
        self.step_delay = step_delay
        self.warmed = True

    def post_warmup_recompiles(self):
        return 0

    def warmup(self):
        return {}

    def prefill(self, slot, prompt, *, seed=0, temperature=0.0, top_k=0):
        self.pool.lengths[slot] = len(prompt)
        last = np.zeros((self.model_cfg.vocab_size,), np.float32)
        return (prompt[-1] + 1) % self.model_cfg.vocab_size, last

    def decode(self, entries):
        if self.step_delay:
            time.sleep(self.step_delay)
        out = {}
        for slot, token, _seed, _temp, _tk in entries:
            self.pool.lengths[slot] += 1
            out[slot] = (token + 1) % self.model_cfg.vocab_size
        return out


def _reference(prompt, n, vocab=211):
    return [(prompt[-1] + 1 + i) % vocab for i in range(n)]


# ------------------------------------------------------------ controller


class TestOverloadController:
    def _ctl(self, **kw):
        clock = _Clock()
        base = dict(
            registry=MetricsRegistry(), queue_hi=4, kv_hi=0.9,
            clear_frac=0.5, hold_s=1.0, max_new_tokens_cap=4,
            clock=clock,
        )
        base.update(kw)
        return OverloadController(**base), clock

    def test_escalates_one_rung_per_hold(self):
        ctl, clock = self._ctl()
        assert ctl.update(queue_depth=10, kv_occupancy=0.0) == 1
        # Still hot immediately after: the hold gates the next rung.
        assert ctl.update(queue_depth=10, kv_occupancy=0.0) == 1
        clock.advance(1.1)
        assert ctl.update(queue_depth=10, kv_occupancy=0.0) == 2
        for _ in range(5):
            clock.advance(1.1)
            ctl.update(queue_depth=10, kv_occupancy=0.0)
        assert ctl.level == MAX_LEVEL  # capped at the top rung

    def test_kv_signal_alone_escalates(self):
        ctl, _ = self._ctl()
        assert ctl.update(queue_depth=0, kv_occupancy=0.95) == 1

    def test_clears_one_rung_per_sustained_hold(self):
        ctl, clock = self._ctl()
        ctl.update(queue_depth=10, kv_occupancy=0.0)
        clock.advance(1.1)
        ctl.update(queue_depth=10, kv_occupancy=0.0)
        assert ctl.level == 2
        # Below the clear watermark, but not yet for a full hold.
        ctl.update(queue_depth=0, kv_occupancy=0.0)
        assert ctl.level == 2
        clock.advance(1.1)
        assert ctl.update(queue_depth=0, kv_occupancy=0.0) == 1
        # The NEXT rung down needs its own full hold.
        assert ctl.update(queue_depth=0, kv_occupancy=0.0) == 1
        clock.advance(1.1)
        assert ctl.update(queue_depth=0, kv_occupancy=0.0) == 0

    def test_between_watermarks_holds_level(self):
        """Hysteresis band: above clear (2 = 0.5*4) but below hi (4)
        neither escalates nor clears."""
        ctl, clock = self._ctl()
        ctl.update(queue_depth=10, kv_occupancy=0.0)
        assert ctl.level == 1
        for _ in range(5):
            clock.advance(1.1)
            ctl.update(queue_depth=3, kv_occupancy=0.0)
        assert ctl.level == 1

    def test_enforcement_by_level(self):
        ctl, _ = self._ctl()
        assert not ctl.sheds("batch") and not ctl.sheds("interactive")
        assert ctl.max_new_cap() is None and not ctl.spec_disabled()
        ctl.level = LEVEL_SHED_BATCH
        assert ctl.sheds("batch") and not ctl.sheds("interactive")
        ctl.level = LEVEL_CAP_TOKENS
        assert ctl.max_new_cap() == 4 and not ctl.spec_disabled()
        ctl.level = LEVEL_NO_SPEC
        assert ctl.spec_disabled() and not ctl.sheds("interactive")
        ctl.level = LEVEL_SHED_INTERACTIVE
        assert ctl.sheds("interactive") and ctl.sheds("batch")

    def test_ttft_signal_uses_recent_window_only(self):
        ctl, clock = self._ctl(ttft_hi_s=0.5)
        ctl.note_ttft(2.0)  # way over the watermark
        assert ctl.update(queue_depth=0, kv_occupancy=0.0) == 1
        # The sample ages out of the window: pressure reads clear.
        clock.advance(10.0)
        assert ctl.ttft_p95() is None
        clock.advance(1.1)
        ctl.update(queue_depth=0, kv_occupancy=0.0)
        clock.advance(1.1)
        assert ctl.update(queue_depth=0, kv_occupancy=0.0) == 0

    def test_disabled_controller_never_moves(self):
        ctl, _ = self._ctl(enabled=False)
        assert ctl.update(queue_depth=1000, kv_occupancy=1.0) == 0
        assert not ctl.sheds("batch") and ctl.max_new_cap() is None

    def test_transitions_counted_logged_and_evented(self):
        ctl, clock = self._ctl()
        ctl.update(queue_depth=10, kv_occupancy=0.0)
        clock.advance(1.1)
        ctl.update(queue_depth=10, kv_occupancy=0.0)
        counters = ctl.registry.counter_values()
        assert counters["serving/brownout_transitions_total"] == 2
        assert counters["serving/brownout_escalations_total"] == 2
        assert ctl.registry.gauge_values()[
            "serving/brownout_level"
        ] == 2.0
        assert [(f, t) for _, f, t, _ in ctl.events] == [(0, 1), (1, 2)]
        assert "queue_depth" in ctl.events[0][3]


# ------------------------------------------------------- SLO admission


class TestSloAdmission:
    def test_interactive_admitted_before_batch(self):
        """Both classes queued before the loop starts, ONE slot: the
        interactive request must be served to completion first even
        though batch was submitted earlier."""
        eng = _FakeEngine(max_slots=1, step_delay=0.002)
        b = ContinuousBatcher(eng)
        order = []
        fut_b = b.submit(Request(prompt=[5], max_new_tokens=3,
                                 slo="batch"))
        fut_i = b.submit(Request(prompt=[9], max_new_tokens=3))
        fut_b.add_done_callback(lambda f: order.append("batch"))
        fut_i.add_done_callback(lambda f: order.append("interactive"))
        b.start()
        try:
            assert fut_i.result(timeout=10).tokens == _reference([9], 3)
            assert fut_b.result(timeout=10).tokens == _reference([5], 3)
        finally:
            b.close(drain=True)
        assert order == ["interactive", "batch"]

    def test_unknown_slo_rejected(self):
        eng = _FakeEngine()
        b = ContinuousBatcher(eng)
        fut = b.submit(Request(prompt=[1], slo="bulk"))
        with pytest.raises(ValueError, match="slo class"):
            fut.result(timeout=5)
        assert b.registry.counter_values()[
            "serving/rejected_total"
        ] == 1
        b.close(drain=False)

    def test_frontend_validates_slo_field(self):
        eng = _FakeEngine()
        b = ContinuousBatcher(eng).start()
        fe = ServingFrontend(b, port=0)
        try:
            status, reply = fe.handle_request(
                {"prompt": [1], "slo": "bulk"}, kind="generate"
            )
            assert status == 400 and "slo" in reply["error"]
            status, reply = fe.handle_request(
                {"prompt": [1], "max_new_tokens": 2, "slo": "batch"},
                kind="generate",
            )
            assert status == 200
            assert reply["tokens"] == _reference([1], 2)
        finally:
            b.close(drain=True)

    def test_per_class_histograms_and_shed_counters(self):
        eng = _FakeEngine(max_slots=4)
        b = ContinuousBatcher(eng).start()
        try:
            futs = [
                b.submit(Request(prompt=[3], max_new_tokens=2,
                                 slo=slo))
                for slo in ("interactive", "batch")
            ]
            for f in futs:
                f.result(timeout=10)
        finally:
            b.close(drain=True)
        hists = b.registry.histogram_summaries()
        for cls in ("interactive", "batch"):
            for name in ("queue_wait", "ttft", "tpot", "e2e"):
                h = hists.get(f"serving/{name}_{cls}")
                assert h and h["count"] >= 1, (name, cls)

    def test_batch_queue_full_sheds_with_class_counter(self):
        """Per-class bounds: the batch queue overflowing sheds BATCH
        (with its class counter) while the interactive queue still
        accepts — batch absorbs the shedding first, structurally."""
        eng = _FakeEngine(max_slots=1, max_queue=1)
        b = ContinuousBatcher(eng)  # not started: pure queue behavior
        first = b.submit(Request(prompt=[1], max_new_tokens=2,
                                 slo="batch"))
        with pytest.raises(QueueFull):
            b.submit(Request(prompt=[3], max_new_tokens=1,
                             slo="batch"))
        counters = b.registry.counter_values()
        assert counters["serving/shed_batch_total"] == 1
        assert counters["serving/shed_total"] == 1
        # The interactive queue is NOT full: its class still flows.
        fut = b.submit(Request(prompt=[4], max_new_tokens=1))
        b.start()
        try:
            assert fut.result(timeout=10).tokens == _reference([4], 1)
            assert first.result(timeout=20).tokens == \
                _reference([1], 2)
        finally:
            b.close(drain=True)


class TestPreemption:
    @pytest.mark.timeout(60)
    def test_interactive_preempts_batch_and_replays_identically(self):
        """One slot held by a long batch request; an interactive
        arrival preempts it (slot freed, batch re-queued), completes
        first, and the batch request then REPLAYS from the prompt with
        a token-identical stream."""
        eng = _FakeEngine(max_slots=1, step_delay=0.01)
        b = ContinuousBatcher(eng).start()
        try:
            fut_b = b.submit(Request(prompt=[7], max_new_tokens=12,
                                     slo="batch"))
            deadline = time.monotonic() + 5
            while not b._active and time.monotonic() < deadline:
                time.sleep(0.005)
            assert b._active, "batch request never started"
            fut_i = b.submit(Request(prompt=[40], max_new_tokens=2))
            res_i = fut_i.result(timeout=15)
            assert res_i.tokens == _reference([40], 2)
            assert not fut_b.done(), (
                "batch should still be re-running after preemption"
            )
            res_b = fut_b.result(timeout=30)
            assert res_b.tokens == _reference([7], 12)
            assert res_b.truncated is None
            assert b.registry.counter_values()[
                "serving/preempted_total"
            ] >= 1
        finally:
            b.close(drain=True)

    def test_interactive_never_preempts_interactive(self):
        eng = _FakeEngine(max_slots=1, step_delay=0.01)
        b = ContinuousBatcher(eng).start()
        try:
            fut_a = b.submit(Request(prompt=[7], max_new_tokens=6))
            deadline = time.monotonic() + 5
            while not b._active and time.monotonic() < deadline:
                time.sleep(0.005)
            fut_b = b.submit(Request(prompt=[9], max_new_tokens=2))
            assert fut_a.result(timeout=15).tokens == _reference([7], 6)
            assert fut_b.result(timeout=15).tokens == _reference([9], 2)
            assert b.registry.counter_values().get(
                "serving/preempted_total", 0
            ) == 0
        finally:
            b.close(drain=True)


# --------------------------------------------------- brownout integration


class TestBrownoutIntegration:
    def test_level1_sheds_batch_submits_only(self):
        eng = _FakeEngine(brownout=True)
        b = ContinuousBatcher(eng).start()
        try:
            b._overload.level = 1
            with pytest.raises(QueueFull, match="brownout"):
                b.submit(Request(prompt=[1], slo="batch"))
            counters = b.registry.counter_values()
            assert counters["serving/shed_batch_total"] == 1
            assert counters["serving/brownout_shed_total"] == 1
            fut = b.submit(Request(prompt=[2], max_new_tokens=1))
            assert fut.result(timeout=10).tokens == _reference([2], 1)
        finally:
            b.close(drain=True)

    def test_level2_caps_generation_as_prefix(self):
        eng = _FakeEngine(brownout=True, brownout_max_new_tokens=3)
        b = ContinuousBatcher(eng).start()
        try:
            b._overload.level = 2
            fut = b.submit(Request(prompt=[5], max_new_tokens=10))
            res = fut.result(timeout=10)
            assert res.truncated == "brownout"
            # A PREFIX of the uncapped stream, exactly cap tokens long.
            assert res.tokens == _reference([5], 10)[:3]
            assert b.registry.counter_values()[
                "serving/brownout_truncated_total"
            ] == 1
        finally:
            b.close(drain=True)

    def test_level4_sheds_interactive_too(self):
        eng = _FakeEngine(brownout=True)
        b = ContinuousBatcher(eng).start()
        try:
            b._overload.level = 4
            with pytest.raises(QueueFull, match="brownout"):
                b.submit(Request(prompt=[1]))
            assert b.registry.counter_values()[
                "serving/shed_interactive_total"
            ] == 1
        finally:
            b.close(drain=True)

    @pytest.mark.timeout(60)
    def test_ladder_engages_under_load_and_clears_idle(self):
        """End-to-end: a slow engine + a queue flood walks the ladder
        up (real transitions, counted), then the idle loop walks it
        fully back to 0 — the hysteresis story, wired."""
        eng = _FakeEngine(
            max_slots=1, max_queue=32, step_delay=0.01,
            brownout=True, brownout_queue_hi=2,
            brownout_hold_s=0.05,
        )
        b = ContinuousBatcher(eng).start()
        try:
            futs = [
                b.submit(Request(prompt=[3], max_new_tokens=4))
                for _ in range(12)
            ]
            deadline = time.monotonic() + 20
            while b.brownout_level == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            assert b.brownout_level >= 1, "ladder never engaged"
            for f in futs:
                try:
                    f.result(timeout=30)
                except QueueFull:
                    pass  # the ladder's own sheds are expected
            deadline = time.monotonic() + 20
            while b.brownout_level > 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert b.brownout_level == 0, "ladder never cleared"
            assert b.registry.counter_values()[
                "serving/brownout_transitions_total"
            ] >= 2  # at least one up AND one down
        finally:
            b.close(drain=True)

    def test_health_exposes_brownout_state(self):
        eng = _FakeEngine(brownout=True)
        b = ContinuousBatcher(eng)
        fe = ServingFrontend(b, port=0)
        b._overload.level = 2
        b._overload.events.append((time.time(), 1, 2, "test"))
        status, body = fe.health_payload()
        assert status == 200
        assert body["brownout_level"] == 2
        assert body["brownout_transitions"] == 1
        b.close(drain=False)


# ------------------------------------------------- flash-crowd golden


class TestFlashCrowdGolden:
    @pytest.mark.timeout(180)
    def test_flash_crowd_sheds_batch_only_interactive_survives(self):
        """THE overload acceptance (ISSUE 13): a seeded 3x flash crowd
        against a 2-replica fleet (real batcher/frontend/router over
        HTTP, deterministic engines). All shedding lands on the batch
        class, every interactive request completes 200 with a stream
        token-identical to the reference (prefix under a brownout
        cap), the ladder engages and fully clears, and interactive
        flash-window TTFT p95 stays within the declared budget of the
        steady window's."""
        import serve_bench

        engines = [
            _FakeEngine(
                max_slots=4, max_queue=64, step_delay=0.004,
                brownout=True, brownout_queue_hi=6,
                brownout_hold_s=0.25, brownout_max_new_tokens=4,
            )
            for _ in range(2)
        ]
        stacks = []
        for eng in engines:
            b = ContinuousBatcher(eng).start()
            fe = ServingFrontend(b, port=0).start()
            stacks.append((b, fe))
        router = Router(
            [f"http://127.0.0.1:{fe.port}" for _, fe in stacks],
            cfg=RouterConfig(
                probe_interval_s=0.05, request_timeout_s=30.0,
            ),
        ).start()
        rfront = RouterFrontend(router, port=0).start()
        try:
            schedule = serve_bench.make_traffic_schedule(
                "flash", 150, rate=120.0, vocab=211, max_len=64,
                max_new=8, batch_fraction=0.5, flash_factor=3.0,
                seed=7,
            )
            outcome = serve_bench.drive_open_loop(
                None, schedule, http_url=rfront.url("/generate"),
                timeout=30.0,
            )
            # Settle: the ladder must walk fully back down.
            deadline = time.monotonic() + 30
            while any(b.brownout_level for b, _ in stacks) \
                    and time.monotonic() < deadline:
                time.sleep(0.05)

            shed_interactive = shed_batch = 0
            for reply, ev in zip(outcome["replies"], schedule):
                assert reply is not None, "request never resolved"
                status, body = reply
                assert status in (200, 503), (status, body)
                if status == 503:
                    if ev["slo"] == "interactive":
                        shed_interactive += 1
                    else:
                        shed_batch += 1
                    continue
                ref = _reference(ev["prompt"], ev["max_new"])
                toks = body["tokens"]
                if body.get("truncated") == "brownout":
                    assert toks == ref[:len(toks)] and toks, (
                        "brownout cap must deliver a stream prefix"
                    )
                else:
                    assert toks == ref, "stream not token-identical"
            # The whole point: batch absorbs the flash crowd.
            assert shed_interactive == 0, (
                f"{shed_interactive} interactive requests shed"
            )
            transitions = sum(
                len(b._overload.events) for b, _ in stacks
            )
            assert transitions >= 2, "brownout ladder never engaged"
            assert all(b.brownout_level == 0 for b, _ in stacks), (
                "brownout ladder never cleared"
            )
            # Interactive latency: flash p95 within budget of steady.
            def p95(phases):
                vals = sorted(
                    r[1]["ttft_s"]
                    for r, ev in zip(outcome["replies"], schedule)
                    if r[0] == 200 and ev["slo"] == "interactive"
                    and ev["phase"] in phases
                )
                return vals[int(0.95 * (len(vals) - 1))] if vals \
                    else None

            steady, flash = p95(("steady",)), p95(("flash",))
            assert steady is not None and flash is not None
            assert flash <= serve_bench.FLASH_TTFT_BUDGET * max(
                steady, 0.05
            ), f"flash p95 {flash:.3f}s vs steady {steady:.3f}s"
        finally:
            rfront.close()
            router.close()
            for b, fe in stacks:
                b.close(drain=True)
                fe.close()


# ------------------------------------------------------------ schema v10


def _build_paged_engine(**kw):
    import serve_bench

    cfg = ServeConfig(
        max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
        kv_block_size=16, **kw,
    )
    return serve_bench.build_smoke_engine(cfg)


class TestSchemaV10:
    def test_stats_line_is_v10_and_validates(self):
        eng = _FakeEngine(brownout=True)
        b = ContinuousBatcher(eng)
        line = json.loads(json.dumps(b.stats_line()))
        assert line["schema_version"] == \
            schema.SERVING_SCHEMA_VERSION == 14
        assert schema.validate_line(line) == []
        assert line["serving"]["brownout_level"] == 0
        assert line["serving"]["shed_interactive"] == 0
        assert line["serving"]["shed_batch"] == 0
        assert line["serving"]["preempted_batch"] == 0

    def test_v10_keys_flagged_on_older_versions(self):
        base = {
            "schema_version": 10, "kind": "serving", "step": 1,
            "time_unix": 1.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {}, "counters": {}, "gauges": {}, "derived": {},
            "serving": {
                "active_requests": 0, "queue_depth": 0, "slots": 4,
                "kv_occupancy": 0.0, "post_warmup_recompiles": 0,
                "draining": 0, "brownout_level": 1,
                "brownout_transitions": 2, "shed_interactive": 0,
                "shed_batch": 3, "preempted_batch": 1,
                "ttft_p95_interactive": 0.01, "ttft_p95_batch": 0.2,
                "queue_wait_p95_interactive": 0.001,
                "queue_wait_p95_batch": 0.1,
                "tpot_p95_interactive": 0.002,
                "tpot_p95_batch": 0.002, "digest_truncated": 0,
            },
        }
        assert schema.validate_line(base) == []
        for version in (4, 5, 6, 7, 8, 9):
            stale = dict(base, schema_version=version)
            problems = schema.validate_line(stale)
            for key in schema.SERVING_KEYS_V10:
                assert any(
                    f"v10 serving key '{key}'" in p for p in problems
                ), (version, key, problems)

    def test_per_class_p95s_on_line_after_traffic(self):
        eng = _FakeEngine()
        b = ContinuousBatcher(eng).start()
        try:
            for slo in ("interactive", "batch"):
                b.submit(Request(
                    prompt=[3], max_new_tokens=2, slo=slo
                )).result(timeout=10)
            line = json.loads(json.dumps(b.stats_line()))
        finally:
            b.close(drain=True)
        assert schema.validate_line(line) == []
        for key in ("ttft_p95_interactive", "ttft_p95_batch",
                    "queue_wait_p95_interactive",
                    "queue_wait_p95_batch"):
            assert isinstance(line["serving"][key], float), key

    def test_router_line_carries_fleet_brownout_view(self):
        r = Router(["http://a:1", "http://b:2"])
        for i, rep in enumerate(r.replicas):
            rep.probed = True
            rep.brownout_level = i * 2   # 0, 2
            rep.brownout_transitions = 3
            rep.digest_truncated = (i == 1)
        line = json.loads(json.dumps(r.stats_line()))
        assert line["schema_version"] == schema.SERVING_SCHEMA_VERSION
        assert schema.validate_line(line) == []
        assert line["serving"]["brownout_level"] == 2  # fleet MAX
        assert line["serving"]["brownout_transitions"] == 6
        assert line["serving"]["digest_truncated"] == 1
        status, health = r.health_payload()
        assert health["brownout_max"] == 2
        assert health["digest_truncated"] is True


class TestDigestTruncation:
    """ISSUE 13 satellite: prefix_digest caps loudly, not silently."""

    @pytest.mark.timeout(300)
    def test_digest_reports_truncation_and_health_exposes_it(self):
        eng = _build_paged_engine()
        pool = eng.pool
        # Publish 3 chained blocks, then cap the digest below that.
        slot = pool.alloc()
        prompt = list(range(48))
        pool.claim_prompt_blocks(slot, prompt)
        pool.insert_prefix(slot, prompt)
        full = pool.prefix_digest()
        assert full["truncated"] is False and len(full["keys"]) == 3
        capped = pool.prefix_digest(max_keys=2)
        assert capped["truncated"] is True
        assert len(capped["keys"]) == 2
        assert capped["blocks"] == 3  # the COUNT stays honest
        # paged_stats carries the numeric flag (0 here: the real cap
        # is DIGEST_MAX_KEYS, far above 3 blocks).
        assert pool.paged_stats()["digest_truncated"] == 0
        b = ContinuousBatcher(eng)
        fe = ServingFrontend(b, port=0)
        _, body = fe.health_payload()
        assert body["digest_truncated"] is False
        line = json.loads(json.dumps(b.stats_line()))
        assert schema.validate_line(line) == []
        assert line["serving"]["digest_truncated"] == 0
        b.close(drain=False)
