"""Known-good JAX-hazard fixture: the repo idioms the pass must NOT
flag — static-marker del, partial-bound bucket ladders, None/string
dispatch, same-statement donate-and-reassign, host int() on the hot
path. Must produce ZERO findings."""

import functools

import jax
import numpy as np


def _impl(bucket, params, tokens, length):
    del bucket  # static: encoded in tokens.shape
    if length is None:  # host-side None dispatch: clean
        return tokens
    if isinstance(tokens, tuple):  # host-side structure dispatch: clean
        tokens = tokens[0]
    if len(tokens) == 4:  # len() of a pytree: host-side shape, clean
        pass
    return tokens


fns = {
    b: jax.jit(functools.partial(_impl, b), donate_argnums=(0,))
    for b in (8, 16)
}


class Engine:
    def _run_compiled(self, kind, fn, *args):
        return fn(*args)

    def stepper(self, tokens, n):
        # Donate-and-reassign in ONE statement (the engine's pool
        # idiom): the donated buffer is a target of the very call.
        self.params, out = self._run_compiled(
            "step", fns[8], self.params, tokens, n
        )
        return out


# graftlint: hot-path
def decode_host(entries):
    slots = [int(e) for e in entries]  # host int(): not a device sync
    return slots
