"""Known-good lock-discipline fixture: every exemption the pass
documents, in one file — must produce ZERO findings."""

import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        # Construction precedes sharing: writes in the defining
        # class's __init__ are exempt.
        self._free = [1, 2, 3]  # guard: self._lock
        self.hits = 0  # guard: self._lock
        self._free = list(self._free)

    def take(self):
        with self._lock:
            if self._free:
                self.hits += 1
                return self._free.pop()
        return None

    def _compact_locked(self):
        # Caller-holds-the-lock suffix convention.
        self._free = sorted(self._free)

    def approx_depth(self):
        return len(self._free)  # graftlint: ignore — racy read is fine here


class Owner:
    """Cross-class guard: Pool-shaped state guarded by the OWNER's
    lock (the router's ReplicaState pattern) — matching is by the
    guard's final component."""

    def __init__(self):
        self._lock = threading.Lock()
        self.state = State()

    def poke(self):
        with self._lock:
            self.state.flag = True


class State:
    def __init__(self):
        self.flag = False  # guard: Owner._lock


_DEPTH = 0  # guard: _STATE_LOCK
_STATE_LOCK = threading.Lock()


def bump():
    global _DEPTH
    with _STATE_LOCK:
        _DEPTH += 1
