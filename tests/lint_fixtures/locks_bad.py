"""Known-bad lock-discipline fixture: every finding here is pinned
exactly by tests/test_lint.py (file NOT collected by pytest — no
test_ prefix — and never imported; graftlint parses it as source)."""

import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._free = [1, 2, 3]  # guard: self._lock
        self.hits = 0  # guard: self._lock

    def take(self):
        with self._lock:
            if self._free:
                self.hits += 1
                return self._free.pop()
        return None

    def peek(self):
        return len(self._free)  # BAD: annotated read outside the lock

    def put(self, x):
        self._free.append(x)  # BAD: annotated mutation outside the lock

    def reset_hits(self):
        self.hits = 0  # BAD: annotated write outside the lock


_DEPTH = 0  # guard: _STATE_LOCK
_STATE_LOCK = threading.Lock()


def bump():
    global _DEPTH
    _DEPTH += 1  # BAD: module-global write outside _STATE_LOCK
