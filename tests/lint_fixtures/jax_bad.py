"""Known-bad JAX-hazard fixture: one pinned true positive per hazard
family (traced branch, traced host sync, hot-path sync,
use-after-donate — incl. the _run_compiled funnel). Never imported;
graftlint parses it as source."""

import jax
import numpy as np


def _step(params, x, flag):
    if flag > 0:  # BAD: python branch on a traced value
        x = x + 1
    y = float(x)  # BAD: host sync on a traced value
    return x * y


step = jax.jit(_step)


def _donor(params, kv):
    return kv


run = jax.jit(_donor, donate_argnums=(1,))


def caller(params, kv):
    out = run(params, kv)
    tail = kv[0]  # BAD: read after kv was donated to `run`
    return out, tail


class Engine:
    def _run_compiled(self, kind, fn, *args):
        return fn(*args)

    def stepper(self, tokens):
        state = make_state()
        out = self._run_compiled("step", run, self.params, state)
        return out, state  # BAD: state was donated through the funnel


def make_state():
    return object()


# graftlint: hot-path
def decode_host(batch):
    return np.asarray(batch)  # BAD: host sync on the marked hot path
