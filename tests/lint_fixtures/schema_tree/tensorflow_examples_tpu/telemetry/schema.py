"""Mini schema module for the drift-pass golden (tests/test_lint.py).

``ghost_key`` is declared but nothing stamps it (unstamped + it is
also absent from the docs); the per-class ``lat_a``/``lat_b`` keys pin
the f-string cartesian expansion against the batcher's loop stamps.
"""

SERVING_KEYS = ("active_requests", "lat_a", "lat_b")
SERVING_KEYS_V6 = ("ghost_key",)
