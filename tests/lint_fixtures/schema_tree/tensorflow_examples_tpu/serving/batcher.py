"""Mini stamper for the drift-pass golden: stamps one declared key,
one ROGUE key no schema tuple declares, the f-string-expanded
per-class keys, and registers one documented + one undocumented
counter."""

CLASSES = ("a", "b")


def stats_line(reg):
    reg.counter("serving/documented_total").inc()
    reg.counter("serving/undocumented_total").inc()
    serving = {"active_requests": 1}
    serving["rogue_key"] = 2
    for cls in CLASSES:
        serving[f"lat_{cls}"] = 0.0
    return serving
