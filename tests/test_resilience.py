"""Fault-injection tests for the resilience layer (ISSUE 1).

Every recovery behavior is exercised on CPU with deterministic faults
(tests/conftest.py ``faults`` fixture -> utils/faults.py): preemption
checkpoints + bitwise-identical resume (MNIST and GPT-2), NaN skip /
rollback / abort policies, the hung-step watchdog (dump and fail-fast),
bounded IO retry, and the poisoned-batch skip counter. Marked ``faults``
(deliberately not ``slow``) so the tier-1 command always runs them.
"""

import json
import logging
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from tensorflow_examples_tpu.data.memory import train_iterator
from tensorflow_examples_tpu.data.sources import synthetic_images
from tensorflow_examples_tpu.train import resilience
from tensorflow_examples_tpu.train.checkpoint import CheckpointManager
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.utils import faults as faults_mod
from tensorflow_examples_tpu.workloads import mnist

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_cfg(**kw):
    defaults = dict(
        device="cpu",
        global_batch_size=64,
        train_steps=12,
        log_every=50,
        learning_rate=1e-2,
        hidden=16,
        num_layers=1,
        dropout=0.0,
        precision="f32",
        checkpoint_every=100,
        watchdog_secs=0,
    )
    defaults.update(kw)
    return mnist.MnistConfig(**defaults)


def _data(n=256):
    return synthetic_images(n=n, shape=(28, 28, 1), num_classes=10, seed=0)


def _leaves(tree):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


# ------------------------------------------------------------ spec parsing


def test_spec_parsing():
    p = faults_mod.parse_spec("sigterm@10,nan@5:2,slow@3:8,ioerr@2,badbatch@1")
    assert p.sigterm_at == frozenset({10})
    assert p.nan_at == frozenset({5, 6})
    assert p.slow_at == {3: 8.0}
    assert p.io_errors == 2
    assert p.bad_batch_at == frozenset({1})
    assert faults_mod.parse_spec("slow@4").slow_at == {4: 5.0}
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults_mod.parse_spec("frobnicate@3")
    with pytest.raises(ValueError, match="needs '@"):
        faults_mod.parse_spec("sigterm")
    with pytest.raises(ValueError, match="malformed"):
        faults_mod.parse_spec("nan@x")


# ----------------------------------------------------------- io retry path


def test_retry_io_recovers(faults):
    faults("ioerr@2")
    calls = []
    out = faults_mod.retry_io(
        lambda: calls.append(1) or 42, "x", backoff_secs=0.001
    )
    assert out == 42 and len(calls) == 1  # fn ran once, after 2 injected errs


def test_retry_io_bounded(faults):
    faults("ioerr@10")
    with pytest.raises(OSError, match="injected io error"):
        faults_mod.retry_io(lambda: 42, "x", attempts=2, backoff_secs=0.001)


def test_sources_read_retries(faults, tmp_path):
    """A real loader path (MNIST IDX) survives transient IO errors."""
    import gzip
    import struct

    imgs = np.zeros((4, 28, 28), np.uint8)
    lbls = np.arange(4, dtype=np.uint8)
    with gzip.open(tmp_path / "train-images-idx3-ubyte.gz", "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", 4, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(tmp_path / "train-labels-idx1-ubyte.gz", "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 1))
        f.write(struct.pack(">I", 4))
        f.write(lbls.tobytes())

    faults_mod.configure_io_retry(3, 0.001)
    try:
        faults("ioerr@2")
        from tensorflow_examples_tpu.data.sources import load_mnist

        ds = load_mnist(str(tmp_path), split="train")
        assert ds.size == 4
        np.testing.assert_array_equal(ds.arrays["label"], lbls)
    finally:
        faults_mod.configure_io_retry(3, 0.25)


# ------------------------------------------------------- poisoned batches


def _batches(n):
    for i in range(n):
        yield {"x": np.full((4,), i, np.float32)}


def test_poisoned_batch_skipped_and_counted(faults):
    import jax
    from jax.sharding import SingleDeviceSharding

    from tensorflow_examples_tpu.data.prefetch import device_prefetch

    sharding = SingleDeviceSharding(jax.devices()[0])
    faults("badbatch@1")
    got = [
        float(b["x"][0])
        for b in device_prefetch(_batches(4), sharding, max_skips=1)
    ]
    assert got == [0.0, 2.0, 3.0]  # batch 1 skipped, rest intact


def test_poisoned_batch_budget_exhausted(faults):
    import jax
    from jax.sharding import SingleDeviceSharding

    from tensorflow_examples_tpu.data.prefetch import device_prefetch

    sharding = SingleDeviceSharding(jax.devices()[0])
    faults("badbatch@1,badbatch@2")  # two bad batches, budget of one
    with pytest.raises(RuntimeError, match="budget max_skipped_batches=1"):
        list(device_prefetch(_batches(5), sharding, max_skips=1))


def test_poisoned_batch_default_propagates_original_error(faults):
    """max_skips=0 (the default) must surface the ORIGINAL exception —
    a deterministic pipeline bug is not 'corrupt input'."""
    import jax
    from jax.sharding import SingleDeviceSharding

    from tensorflow_examples_tpu.data.prefetch import device_prefetch

    sharding = SingleDeviceSharding(jax.devices()[0])
    faults("badbatch@1")
    with pytest.raises(TypeError, match="not a valid JAX array type"):
        list(device_prefetch(_batches(4), sharding))


# ------------------------------------------------- preemption + resume


@pytest.mark.timeout(300)
def test_preempt_resume_bitwise_mnist(faults, tmp_path, devices):
    """SIGTERM mid-run -> clean Preempted exit with a checkpoint; the
    resumed run's final params are BITWISE identical to an uninterrupted
    run's (stateless-resumable input order + step-keyed rng)."""
    ds = _data()

    def data_fn(start):
        return train_iterator(ds, 64, seed=7, start_step=start)

    cfg_a = tiny_cfg(train_steps=8, workdir=str(tmp_path / "a"))
    tr_a = Trainer(mnist.make_task(cfg_a), cfg_a)
    tr_a.fit(data_fn)

    wd = str(tmp_path / "b")
    cfg_b = tiny_cfg(train_steps=8, workdir=wd)
    tr_b1 = Trainer(mnist.make_task(cfg_b), cfg_b)
    faults("sigterm@4")
    with pytest.raises(resilience.Preempted) as exc:
        tr_b1.fit(data_fn)
    assert exc.value.code == 0  # clean exit code
    assert exc.value.step == 5  # boundary after the in-flight step
    assert CheckpointManager(wd).latest_step() == 5

    faults_mod.clear()
    tr_b2 = Trainer(mnist.make_task(cfg_b), cfg_b)
    tr_b2.fit(data_fn)
    assert int(tr_b2.state.step) == 8
    for a, b in zip(_leaves(tr_a.state.params), _leaves(tr_b2.state.params)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.timeout(300)
def test_preempt_resume_bitwise_gpt2(faults, tmp_path, devices):
    from tensorflow_examples_tpu.workloads import gpt2

    def cfg_for(workdir):
        return gpt2.Gpt2Config(
            vocab_size=64, seq_len=16, num_layers=1, num_heads=2, d_model=16,
            dropout=0.0, attention="xla", global_batch_size=16,
            train_steps=6, warmup_steps=2, learning_rate=3e-3, log_every=50,
            checkpoint_every=100, eval_every=0, precision="f32",
            watchdog_secs=0, workdir=workdir,
        )

    train_ds, _ = gpt2.datasets(cfg_for(""))

    def data_fn(start):
        return train_iterator(train_ds, 16, seed=3, start_step=start)

    cfg_a = cfg_for(str(tmp_path / "a"))
    tr_a = Trainer(gpt2.make_task(cfg_a), cfg_a)
    tr_a.fit(data_fn)

    cfg_b = cfg_for(str(tmp_path / "b"))
    tr_b1 = Trainer(gpt2.make_task(cfg_b), cfg_b)
    faults("sigterm@3")
    with pytest.raises(resilience.Preempted) as exc:
        tr_b1.fit(data_fn)
    assert exc.value.code == 0

    faults_mod.clear()
    tr_b2 = Trainer(gpt2.make_task(cfg_b), cfg_b)
    tr_b2.fit(data_fn)
    assert int(tr_b2.state.step) == 6
    for a, b in zip(_leaves(tr_a.state.params), _leaves(tr_b2.state.params)):
        np.testing.assert_array_equal(a, b)


def test_preempt_without_workdir_still_exits_cleanly(faults, devices):
    cfg = tiny_cfg(train_steps=6)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("sigterm@2")
    with pytest.raises(resilience.Preempted) as exc:
        trainer.fit(train_iterator(_data(), 64, seed=0))
    assert exc.value.code == 0 and exc.value.signum == signal.SIGTERM


# ------------------------------------------------------- bad-step guards


@pytest.mark.timeout(300)
def test_nan_skip_policy(faults, devices):
    """An injected NaN batch is skipped ON DEVICE: params stay finite,
    training continues, and the bad step is counted."""
    from tensorflow_examples_tpu.telemetry.registry import default_registry

    before = default_registry().counter_values().get("resilience/bad_steps", 0)
    cfg = tiny_cfg(train_steps=10, bad_step_policy="skip")
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("nan@3")
    metrics = trainer.fit(train_iterator(_data(), 64, seed=0))
    assert int(trainer.state.step) == 10
    for leaf in _leaves(trainer.state.params):
        assert np.isfinite(leaf).all()
    assert np.isfinite(metrics["loss"])  # finite-mean excludes the NaN step
    assert trainer._guard.bad_steps_seen == 1
    assert metrics["bad_step"] > 0
    # ISSUE 2: the skip is no longer write-only — it reaches the
    # telemetry registry (cumulative across the process, hence delta).
    after = default_registry().counter_values()["resilience/bad_steps"]
    assert after - before == 1


@pytest.mark.timeout(300)
def test_nan_rollback_policy(faults, tmp_path, devices):
    """K consecutive NaN steps trigger a restore of the latest checkpoint
    and a replay; transient faults (fire-once) converge."""
    ds = _data()

    def data_fn(start):
        return train_iterator(ds, 64, seed=5, start_step=start)

    cfg = tiny_cfg(
        train_steps=12,
        checkpoint_every=4,
        workdir=str(tmp_path),
        bad_step_policy="rollback",
        bad_step_patience=3,
    )
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("nan@6:3")
    trainer.fit(data_fn)
    assert trainer._guard.rollbacks == 1
    assert int(trainer.state.step) == 12
    for leaf in _leaves(trainer.state.params):
        assert np.isfinite(leaf).all()


def test_abort_policy(faults, devices):
    cfg = tiny_cfg(train_steps=10, bad_step_policy="abort")
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("nan@2")
    with pytest.raises(resilience.BadStepError, match="policy=abort"):
        trainer.fit(train_iterator(_data(), 64, seed=0))


def test_skip_policy_aborts_after_patience(faults, devices):
    cfg = tiny_cfg(
        train_steps=12, bad_step_policy="skip", bad_step_patience=3
    )
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("nan@2:6")
    with pytest.raises(resilience.BadStepError, match="consecutive bad steps"):
        trainer.fit(train_iterator(_data(), 64, seed=0))


def test_rollback_needs_a_checkpoint(faults, devices):
    cfg = tiny_cfg(
        train_steps=10, bad_step_policy="rollback", bad_step_patience=2
    )
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("nan@2:4")
    with pytest.raises(resilience.BadStepError, match="needs a checkpoint"):
        trainer.fit(train_iterator(_data(), 64, seed=0))


def test_guard_spike_detection():
    g = resilience.BadStepGuard("abort", spike_factor=5.0)
    for step, loss in enumerate([1.0, 1.1, 0.9, 1.0]):
        g.observe(step, {"loss": np.float32(loss), "bad_step": np.float32(0)})
    assert g.poll(drain=True) is None
    g.observe(4, {"loss": np.float32(100.0), "bad_step": np.float32(0)})
    with pytest.raises(resilience.BadStepError, match="bad train step 4"):
        g.poll(drain=True)


def test_guard_repeat_rollback_aborts():
    g = resilience.BadStepGuard("rollback", patience=1)
    g.note_rollback(4)
    with pytest.raises(resilience.BadStepError, match="not transient"):
        g.note_rollback(4)


def test_guard_rejects_unknown_policy():
    with pytest.raises(ValueError, match="bad_step_policy"):
        resilience.BadStepGuard("explode")


def test_invalid_policy_rejected_before_watchdog_starts(devices):
    """Config validation precedes thread/handler setup: a typo'd policy
    must not leak a running watchdog thread out of fit()."""
    import threading

    cfg = tiny_cfg(train_steps=2, bad_step_policy="skp", watchdog_secs=5)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    with pytest.raises(ValueError, match="bad_step_policy"):
        trainer.fit(train_iterator(_data(), 64, seed=0))
    leaked = [t for t in threading.enumerate() if t.name == "train-watchdog"]
    assert not leaked


# ------------------------------------------------------------- watchdog


def test_watchdog_reports_phase():
    import time

    from tensorflow_examples_tpu.utils.diagnostics import Watchdog

    hangs = []
    wd = Watchdog(
        0.15, on_hang=lambda step, stalled: hangs.append((step, stalled)),
        poll_s=0.03,
    ).start()
    try:
        wd.ping(5)
        wd.enter("input_fetch")
        time.sleep(0.4)
        assert hangs and hangs[0][0] == 5
        assert wd._phase == "input_fetch"
    finally:
        wd.stop()


def test_watchdog_fatal_callback():
    import time

    from tensorflow_examples_tpu.utils.diagnostics import Watchdog

    fatals = []
    wd = Watchdog(
        0.1,
        fatal_timeout_s=0.2,
        on_hang=lambda *a: None,
        on_fatal=lambda step, stalled: fatals.append(stalled),
        poll_s=0.03,
    ).start()
    try:
        wd.ping(1)
        time.sleep(0.5)
        assert fatals and fatals[0] >= 0.2
    finally:
        wd.stop()


@pytest.mark.timeout(300)
def test_watchdog_trips_on_stalled_batch(faults, devices, caplog):
    """An injected slow batch fetch trips the in-loop watchdog, which
    names the stalled phase in its diagnostic dump."""
    cfg = tiny_cfg(train_steps=8, watchdog_secs=0.4)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("slow@5:1.5")
    with caplog.at_level(logging.ERROR, logger="tensorflow_examples_tpu"):
        trainer.fit(train_iterator(_data(), 64, seed=0))
    dumps = [r for r in caplog.records if "WATCHDOG" in r.getMessage()]
    assert dumps, "watchdog never fired on the stalled fetch"
    assert "input_fetch" in dumps[0].getMessage()


@pytest.mark.timeout(300)
def test_watchdog_trips_on_startup_stall(faults, devices, caplog):
    """A wedged input pipeline on the VERY FIRST fetch (before any step
    completes) must still trip the watchdog: fetch-stall detection arms
    at the fetch, pausing only for the first step's jit compile."""
    cfg = tiny_cfg(train_steps=4, watchdog_secs=0.4)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("slow@0:1.5")
    with caplog.at_level(logging.ERROR, logger="tensorflow_examples_tpu"):
        trainer.fit(train_iterator(_data(), 64, seed=0))
    assert any(
        "WATCHDOG" in r.getMessage() and "input_fetch" in r.getMessage()
        for r in caplog.records
    ), "startup input stall went undetected"


# ------------------------------------------------ checkpoint satellites


def test_checkpoint_context_manager(tmp_path, devices):
    cfg = tiny_cfg(train_steps=2)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    with CheckpointManager(str(tmp_path)) as ckpt:
        ckpt.save(2, trainer.state)
        # async save may still be in flight here; __exit__ must wait it out
    assert CheckpointManager(str(tmp_path)).latest_step() == 2


@pytest.mark.timeout(300)
def test_ckpt_closed_on_fit_exception(faults, tmp_path, devices):
    """A crash mid-run must not abandon the in-flight async save: the
    exception path waits + closes, leaving a readable latest checkpoint."""
    cfg = tiny_cfg(
        train_steps=10, checkpoint_every=2, workdir=str(tmp_path)
    )
    trainer = Trainer(mnist.make_task(cfg), cfg)
    faults("badbatch@6")  # max_skipped_batches=0 -> poisoned batch is fatal
    with pytest.raises(TypeError, match="not a valid JAX array type"):
        trainer.fit(train_iterator(_data(), 64, seed=0))
    assert trainer._ckpt is None  # closed + cleared on the exception path
    step = CheckpointManager(str(tmp_path)).latest_step()
    assert step is not None and step >= 2
    restored = CheckpointManager(str(tmp_path)).restore_latest(
        Trainer(mnist.make_task(cfg), cfg).state
    )
    assert restored is not None and int(restored[1]) == step


def test_restore_validates_structure(tmp_path, devices):
    """Restoring into a drifted model config fails up front with the
    offending paths, not deep inside orbax."""
    cfg_small = tiny_cfg(train_steps=2, hidden=16)
    cfg_big = tiny_cfg(train_steps=2, hidden=32)
    with CheckpointManager(str(tmp_path), async_save=False) as ckpt:
        ckpt.save(1, Trainer(mnist.make_task(cfg_small), cfg_small).state)
    big = Trainer(mnist.make_task(cfg_big), cfg_big)
    with pytest.raises(ValueError, match="shape mismatch") as exc:
        CheckpointManager(str(tmp_path)).restore_latest(big.state)
    assert "params" in str(exc.value)  # names the drifted path


class TestCheckpointIntegrity:
    """ISSUE 10 satellite: sha256 manifests at save, verify-and-fall-
    back at restore — a torn checkpoint degrades to the newest intact
    step with a WARNING naming the corrupt file, never an opaque orbax
    error."""

    def _save_steps(self, tmp_path, trainer, steps=(1, 2, 3)):
        with CheckpointManager(str(tmp_path)) as ckpt:
            for s in steps:
                ckpt.save(s, trainer.state)

    def _corrupt_newest(self, tmp_path):
        """Flip a byte in a manifest-covered data file of the newest
        step; returns its manifest-relative name."""
        import glob

        step_dir = os.path.join(
            str(tmp_path), "checkpoints",
            str(CheckpointManager(str(tmp_path)).latest_step()),
        )
        with open(
            os.path.join(step_dir, "manifest.sha256.json")
        ) as f:
            files = json.load(f)["files"]
        victim = next(
            rel for rel in sorted(files)
            if os.path.getsize(os.path.join(step_dir, rel)) > 0
            and "/d/" in rel
        )
        full = os.path.join(step_dir, victim)
        with open(full, "r+b") as f:
            data = bytearray(f.read())
            data[len(data) // 2] ^= 0xFF
            f.seek(0)
            f.write(data)
        return victim

    @pytest.mark.timeout(300)
    def test_manifest_written_for_every_committed_step(
        self, tmp_path, devices
    ):
        cfg = tiny_cfg(train_steps=2)
        self._save_steps(tmp_path, Trainer(mnist.make_task(cfg), cfg))
        for step in (1, 2, 3):
            path = os.path.join(
                str(tmp_path), "checkpoints", str(step),
                "manifest.sha256.json",
            )
            assert os.path.isfile(path), f"step {step} not stamped"
            with open(path) as f:
                doc = json.load(f)
            assert doc["step"] == step and doc["files"]
        mngr = CheckpointManager(str(tmp_path))
        assert mngr.verify_step_integrity(3) == []
        mngr.close()

    @pytest.mark.timeout(300)
    def test_corrupt_latest_falls_back_with_named_file(
        self, tmp_path, devices, caplog
    ):
        cfg = tiny_cfg(train_steps=2)
        trainer = Trainer(mnist.make_task(cfg), cfg)
        self._save_steps(tmp_path, trainer)
        victim = self._corrupt_newest(tmp_path)
        mngr = CheckpointManager(str(tmp_path))
        problems = mngr.verify_step_integrity(3)
        assert problems and victim in problems[0]
        with caplog.at_level(
            logging.WARNING, logger="tensorflow_examples_tpu"
        ):
            restored = mngr.restore_latest(trainer.state)
        mngr.close()
        assert restored is not None and int(restored[1]) == 2
        warned = " ".join(
            r.getMessage() for r in caplog.records
            if "integrity" in r.getMessage()
        )
        assert victim in warned  # the WARNING names the corrupt file

    @pytest.mark.timeout(300)
    def test_all_steps_corrupt_raises_with_names(
        self, tmp_path, devices
    ):
        cfg = tiny_cfg(train_steps=2)
        trainer = Trainer(mnist.make_task(cfg), cfg)
        self._save_steps(tmp_path, trainer, steps=(1,))
        victim = self._corrupt_newest(tmp_path)
        mngr = CheckpointManager(str(tmp_path))
        with pytest.raises(RuntimeError, match="corrupt") as exc:
            mngr.restore_latest(trainer.state)
        mngr.close()
        assert victim in str(exc.value)

    @pytest.mark.timeout(300)
    def test_pre_manifest_checkpoints_still_restore(
        self, tmp_path, devices
    ):
        """A checkpoint from before this PR (no manifest) verifies
        vacuously and restores exactly as before."""
        cfg = tiny_cfg(train_steps=2)
        trainer = Trainer(mnist.make_task(cfg), cfg)
        self._save_steps(tmp_path, trainer, steps=(4,))
        mpath = os.path.join(
            str(tmp_path), "checkpoints", "4", "manifest.sha256.json"
        )
        os.unlink(mpath)  # simulate the pre-ISSUE-10 layout
        mngr = CheckpointManager(str(tmp_path))
        assert mngr.verify_step_integrity(4) == []
        restored = mngr.restore_latest(trainer.state)
        mngr.close()
        assert restored is not None and int(restored[1]) == 4


# ------------------------------------------------- end-to-end CLI chaos


def _run_cli(script, extra_flags, spec, timeout=240):
    env = dict(os.environ)
    env[faults_mod.ENV_VAR] = spec
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, os.path.join(REPO, script), "--device=cpu"]
        + extra_flags,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )


@pytest.mark.slow
@pytest.mark.timeout(420)
def test_cli_watchdog_fail_fast_exit_code(tmp_path):
    """A hung input fetch past watchdog_fatal_secs kills the process with
    the HUNG_EXIT_CODE signature instead of wedging the slice."""
    from tensorflow_examples_tpu.utils.diagnostics import HUNG_EXIT_CODE

    proc = _run_cli(
        "examples/mnist/train.py",
        [
            "--train_steps=50", "--global_batch_size=64", "--hidden=16",
            "--num_layers=1", "--log_every=5", "--checkpoint_every=0",
            "--watchdog_secs=1", "--watchdog_fatal_secs=3",
        ],
        "slow@4:60",
    )
    assert proc.returncode == HUNG_EXIT_CODE, (
        proc.returncode,
        proc.stdout[-2000:],
        proc.stderr[-2000:],
    )
    assert "WATCHDOG" in proc.stderr


@pytest.mark.timeout(420)
def test_fault_inject_tool_standalone(tmp_path):
    """tools/fault_inject.py arms any workload CLI via the env var."""
    wd = str(tmp_path / "run")
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "fault_inject.py"),
            "--spec", "sigterm@2", "--",
            sys.executable, os.path.join(REPO, "examples", "mnist", "train.py"),
            "--device=cpu", "--train_steps=20", "--global_batch_size=64",
            "--hidden=16", "--num_layers=1", "--checkpoint_every=100",
            f"--workdir={wd}", "--watchdog_secs=0",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "exited cleanly" in proc.stdout
    assert CheckpointManager(wd).latest_step() == 3
