"""Test harness: 8 fake CPU devices (SURVEY.md §4).

All tests run on the CPU backend with
``--xla_force_host_platform_device_count=8`` so mesh/sharding/collective
logic (psum, all_gather, ppermute ring attention, TP shard_map) is
exercised multi-device without TPU hardware. Must be set before jax
initializes — hence here, at conftest import time.
"""

import os
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

# The session sitecustomize pre-imports jax and pins the experimental
# axon TPU plugin, so the env vars above can be too late; the config
# update path still works as long as no backend has been initialized.
jax.config.update("jax_platforms", "cpu")


# ------------------------------------------------------------- timeouts
#
# ``@pytest.mark.timeout(N)`` is ENFORCED here (pytest-timeout is not in
# the image and the environment is pip-install-free): a SIGALRM fires
# after N seconds and fails the test with a TimeoutError — same
# mechanism as pytest-timeout's default "signal" method. Limitation
# (shared with pytest-timeout): the alarm interrupts Python bytecode,
# not a wedged C call that never re-enters the interpreter; the
# distributed tests therefore ALSO bound their subprocesses with
# ``communicate(timeout=...)`` as a second line of defense.
#
# TEST_NO_TIMEOUTS=1 disables the alarms entirely: the TPU harvester
# (tools/lib_bounded.sh) SIGSTOPs a running ``pytest tests/`` for the
# length of a live window, and alarm(2) is real time — it keeps ticking
# while the process is stopped, so every paused test would "time out"
# the moment it resumes. A suite run that may span a live window sets
# the knob and relies on an outer bound instead.


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    import signal

    marker = item.get_closest_marker("timeout")
    seconds = int(marker.args[0]) if marker and marker.args else 0
    if (
        seconds <= 0
        or not hasattr(signal, "SIGALRM")
        or os.environ.get("TEST_NO_TIMEOUTS", "") not in ("", "0")
    ):
        return (yield)

    def on_alarm(signum, frame):
        raise TimeoutError(
            f"test exceeded its {seconds}s timeout marker (frame: "
            f"{frame.f_code.co_filename}:{frame.f_lineno})"
        )

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def faults():
    """Arm a deterministic fault plan for the duration of one test.

    Usage::

        def test_x(faults):
            engine = faults("sigterm@5,ioerr@2")
            ...

    The plan is torn down afterwards even if the test dies mid-fault.
    Spec grammar: tensorflow_examples_tpu/utils/faults.py (sigterm@N,
    nan@N[:M], slow@N[:S], ioerr@K, badbatch@N).
    """
    from tensorflow_examples_tpu.utils import faults as faults_mod

    def arm(spec: str):
        return faults_mod.install(spec)

    yield arm
    faults_mod.clear()


@pytest.fixture
def serve_faults():
    """Arm a deterministic SERVING fault plan for one test (ISSUE 10).

    Usage::

        def test_x(serve_faults):
            engine = serve_faults("crash@1:4,badhealth@0:3")
            ...

    Spec grammar: tensorflow_examples_tpu/utils/faults.py serve side
    (crash@R:N, slowrep@R:S, transport@R:K, kvexhaust@R:N,
    badhealth@R:K). Torn down afterwards even if the test dies
    mid-fault.
    """
    from tensorflow_examples_tpu.utils import faults as faults_mod

    def arm(spec: str):
        return faults_mod.serve_install(spec)

    yield arm
    faults_mod.serve_clear()


@pytest.fixture(scope="session")
def devices():
    d = jax.devices()
    assert len(d) == 8, f"expected 8 fake CPU devices, got {len(d)}"
    return d


@pytest.fixture
def mesh8():
    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=8))


# ----------------------------------------------- ISSUE 14: race guards
#
# Two autouse guards arm the serving-fleet test modules (the tiers
# with real thread traffic — chaos, router, overload, serving):
#
# * lock-order cycle detector (analysis/lockorder.py): every
#   package-allocated threading.Lock/RLock is wrapped while the test
#   runs; acquisitions build a held-before graph and a cycle is a
#   failure AT ORDERING-ESTABLISHMENT time — no actual deadlock (or
#   lucky interleaving) needed. This is the runtime complement of
#   graftlint's static lock pass (docs/static_analysis.md).
# * thread-leak guard: a serving/router/chaos/overload test that
#   leaves a batcher/probe/supervisor/autoscaler/worker loop thread
#   behind fails loudly instead of slowing every later test.

_LOCKORDER_MODULES = (
    "test_chaos.py",
    "test_router.py",
    "test_overload.py",
    "test_journal.py",
    "test_slo.py",
)
_THREAD_GUARD_MODULES = _LOCKORDER_MODULES + ("test_serving.py",)

# Loop/pool threads repo code owns; anything with these names still
# alive after a test (plus a grace period for joins in teardown
# paths) is an orphan. Transient per-request threads (router-dispatch/
# router-hedge, http.server handler threads) are excluded: an
# abandoned hedge loser may legally outlive its request by design.
_OWNED_THREAD_NAMES = (
    "serving-batcher",
    "serving-frontend",
    "router-probe",
    "router-frontend",
    "router-standby",
    "canary-prober",
    "replica-supervisor",
    "fleet-autoscaler",
    "telemetry-metrics-server",
    "train-watchdog",
    "input_worker",
)


def _owned(thread) -> bool:
    name = thread.name or ""
    return any(name.startswith(p) for p in _OWNED_THREAD_NAMES)


@pytest.fixture(autouse=True)
def _serving_thread_leak_guard(request):
    if request.node.fspath.basename not in _THREAD_GUARD_MODULES:
        yield
        return
    import threading as _threading

    before = set(_threading.enumerate())
    yield
    deadline = time.monotonic() + 5.0
    leaked = []
    while True:
        leaked = [
            t for t in _threading.enumerate()
            if t not in before and t.is_alive() and _owned(t)
        ]
        if not leaked or time.monotonic() > deadline:
            break
        time.sleep(0.05)
    assert not leaked, (
        "test leaked serving loop thread(s): "
        f"{[t.name for t in leaked]} — close() the batcher/router/"
        "supervisor/pool it started (ISSUE 14 thread-leak guard)"
    )


@pytest.fixture(autouse=True)
def _lock_order_guard(request):
    if request.node.fspath.basename not in _LOCKORDER_MODULES:
        yield
        return
    from tensorflow_examples_tpu.analysis import lockorder

    mon = lockorder.arm()
    try:
        yield
    finally:
        lockorder.disarm()
    assert not mon.violations, (
        "lock-order cycle(s) established during this test (deadlock "
        "hazard even if this run did not interleave into it):\n  "
        + "\n  ".join(mon.violations)
    )
