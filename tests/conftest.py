"""Test harness: 8 fake CPU devices (SURVEY.md §4).

All tests run on the CPU backend with
``--xla_force_host_platform_device_count=8`` so mesh/sharding/collective
logic (psum, all_gather, ppermute ring attention, TP shard_map) is
exercised multi-device without TPU hardware. Must be set before jax
initializes — hence here, at conftest import time.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

# The session sitecustomize pre-imports jax and pins the experimental
# axon TPU plugin, so the env vars above can be too late; the config
# update path still works as long as no backend has been initialized.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    d = jax.devices()
    assert len(d) == 8, f"expected 8 fake CPU devices, got {len(d)}"
    return d


@pytest.fixture
def mesh8():
    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=8))
