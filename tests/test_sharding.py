"""Unified sharding subsystem (ISSUE 7): one ShardingConfig drives 2-D
GSPMD training, ZeRO-1 optimizer sharding, reshardable checkpoints, and
sharded serving.

The load-bearing claims, each pinned here:

* a GPT-2 step on a (2,2) or (4,2) CPU mesh matches the 1-device loss
  trajectory within f32 reduction-order tolerance;
* a checkpoint written on an 8-device mesh restores BITWISE-identically
  onto 1 device and onto a differently shaped 2-D mesh, while a
  rules-table drift fails with a named ShardingMismatchError;
* ZeRO-1 cuts measured per-device optimizer bytes ≥ 4x on an 8-way
  batch mesh without changing the math;
* the serving engine placed by the same config keeps batched output
  token-identical to the unbatched reference with zero post-warmup
  recompiles.
"""

import dataclasses
import json
import os
import sys

import numpy as np
import pytest

from tensorflow_examples_tpu.core.mesh import AxisNames
from tensorflow_examples_tpu.models import transformer
from tensorflow_examples_tpu.sharding import (
    ResolvedSharding,
    ShardingConfig,
    ShardingMismatchError,
    resolve_params,
)
from tensorflow_examples_tpu.sharding.config import (
    rules_from_json,
    rules_to_json,
    spec_from_json,
    spec_to_json,
)
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import gpt2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def tiny_cfg(**kw):
    base = dict(
        vocab_size=64,
        seq_len=16,
        num_layers=2,
        num_heads=4,
        d_model=32,
        dropout=0.0,
        attention="xla",
        global_batch_size=16,
        train_steps=3,
        warmup_steps=5,
        learning_rate=3e-3,
        log_every=10,
        checkpoint_every=0,
        eval_every=0,
        precision="f32",
        watchdog_secs=0,
    )
    base.update(kw)
    return gpt2.Gpt2Config(**base)


def gpt2_sharding(mesh: dict, **kw) -> ShardingConfig:
    """A config with the GPT-2 rules EMBEDDED (serialized round-trip),
    so training exercises the config's table, not the task fallback."""
    return ShardingConfig(
        mesh=mesh, rules=rules_to_json(transformer.GPT2_RULES), **kw
    )


def make_trainer(cfg, sc: ShardingConfig) -> Trainer:
    mesh = sc.build_mesh()
    task = gpt2.make_task(cfg, mesh=mesh)
    return Trainer(task, cfg, mesh=mesh, sharding=sc)


def run_steps(trainer: Trainer, cfg, n: int) -> list[float]:
    """n deterministic train steps off one synthetic token stream."""
    import jax

    rng = np.random.RandomState(0)
    losses = []
    state = trainer.state
    for _ in range(n):
        batch = {
            "tokens": rng.randint(
                0, cfg.vocab_size, size=(cfg.global_batch_size,
                                         cfg.seq_len + 1)
            ).astype(np.int32)
        }
        state, metrics = trainer._train_step(
            state, trainer._put_batch(batch)
        )
        losses.append(float(metrics["loss"]))
    trainer.state = state
    del jax
    return losses


# ----------------------------------------------------------- config unit


class TestShardingConfig:
    def test_spec_json_roundtrip(self):
        from jax.sharding import PartitionSpec as P

        for spec in (P(), P("data"), P(None, "model"),
                     P(("data", "fsdp"), None, "model")):
            assert spec_from_json(spec_to_json(spec)) == spec

    def test_rules_roundtrip_resolves_identically(self):
        rt = rules_from_json(rules_to_json(transformer.GPT2_RULES))
        for path in (
            "h_0/attn/qkv/kernel", "h_3/mlp_fc/kernel",
            "h_1/mlp_proj/bias", "wte/embedding", "ln_f/scale",
        ):
            assert rt.spec_for(path) == transformer.GPT2_RULES.spec_for(
                path
            ), path

    def test_json_dict_roundtrip(self):
        sc = gpt2_sharding({"data": 2, "model": 4}, zero1=True)
        rt = ShardingConfig.from_json_dict(sc.to_json_dict())
        assert rt == sc

    def test_save_load_with_extra(self, tmp_path):
        sc = gpt2_sharding({"data": 2, "model": 2})
        path = str(tmp_path / "sharding.json")
        sc.save(path, extra={"param_sharding_digest": "abc123"})
        loaded, extra = ShardingConfig.load_with_extra(path)
        assert loaded == sc
        assert extra["param_sharding_digest"] == "abc123"
        # A bare config object (no wrapper) also loads.
        with open(path, "w") as f:
            json.dump(sc.to_json_dict(), f)
        assert ShardingConfig.load(path) == sc

    def test_unknown_axis_rejected(self):
        with pytest.raises(ValueError, match="unknown mesh axes"):
            ShardingConfig(mesh={"banana": 2})
        with pytest.raises(ValueError, match="positive int"):
            ShardingConfig(mesh={"model": 0})
        with pytest.raises(ValueError, match="unknown sharding config"):
            ShardingConfig.from_json_dict({"mesh": {}, "nope": 1})

    def test_build_mesh_uses_prefix_of_devices(self, devices):
        mesh = ShardingConfig(mesh={"data": 2, "model": 2}).build_mesh()
        assert mesh.devices.size == 4
        one = ShardingConfig(mesh={"data": 1}).build_mesh()
        assert one.devices.size == 1
        full = ShardingConfig().build_mesh()  # data=-1: all devices
        assert full.devices.size == 8
        with pytest.raises(ValueError, match="needs 16 devices"):
            ShardingConfig(mesh={"data": 4, "model": 4}).build_mesh()

    def test_batch_sharding_follows_config_axes(self):
        from jax.sharding import PartitionSpec as P

        sc = ShardingConfig(mesh={"data": 2, "model": 2})
        mesh = sc.build_mesh()
        assert sc.batch_sharding(mesh).spec == P(("data",))
        assert sc.bundle_sharding(mesh).spec == P(None, ("data",))


# ---------------------------------------------------------- resolve unit


class TestResolve:
    def _abstract_params(self, cfg):
        import jax

        model = transformer.Transformer(gpt2.model_config(cfg))
        return jax.eval_shape(
            lambda r: model.init({"params": r},
                                 np.zeros((1, cfg.seq_len), np.int32)),
            jax.random.PRNGKey(0),
        )["params"]

    def test_digest_is_mesh_shape_independent(self):
        cfg = tiny_cfg()
        params = self._abstract_params(cfg)
        rules = transformer.GPT2_RULES
        d = {
            name: resolve_params(
                params, gpt2_sharding(mesh).build_mesh(), rules
            ).digest()
            for name, mesh in (
                ("2x2", {"data": 2, "model": 2}),
                ("4x2", {"data": 4, "model": 2}),
                ("1x1", {"data": 1}),
            )
        }
        assert d["2x2"] == d["4x2"] == d["1x1"]
        # A rules change moves the digest.
        from tensorflow_examples_tpu.core.sharding import ShardingRules

        other = resolve_params(
            params,
            gpt2_sharding({"data": 2, "model": 2}).build_mesh(),
            ShardingRules(),
        ).digest()
        assert other != d["2x2"]

    def test_byte_totals_split_replicated_vs_sharded(self):
        cfg = tiny_cfg()
        params = self._abstract_params(cfg)
        mesh = gpt2_sharding({"data": 1, "model": 2}).build_mesh()
        resolved = resolve_params(params, mesh, transformer.GPT2_RULES)
        totals = resolved.byte_totals()
        assert totals["sharded_per_device_bytes"] > 0
        assert totals["replicated_per_device_bytes"] > 0  # embeddings
        assert (
            totals["per_device_bytes"]
            == totals["sharded_per_device_bytes"]
            + totals["replicated_per_device_bytes"]
        )
        assert totals["per_device_bytes"] < totals["global_bytes"]
        # The table renders every row + the totals line.
        table = resolved.table_str()
        assert "wte/embedding" in table and "replicated" in table
        # On a 1-device mesh everything is (locally) replicated.
        mesh1 = ShardingConfig(mesh={"data": 1}).build_mesh()
        r1 = resolve_params(params, mesh1, transformer.GPT2_RULES)
        t1 = r1.byte_totals()
        assert t1["per_device_bytes"] == t1["global_bytes"]
        assert isinstance(r1, ResolvedSharding)


# -------------------------------------------------- training acceptance


class TestDigestAgreement:
    """ISSUE 8 satellite (ROADMAP 1d): sharding.json is written by
    process 0 only and restore validation is per-process — the fit-
    start allgather is the cross-host agreement check, failing with
    the mismatching host NAMED before any restore runs."""

    DIGEST_A = "0123456789abcdef"
    DIGEST_B = "fedcba9876543210"

    def _gather(self, rows):
        def allgather(vec):
            return np.stack(
                [np.frombuffer(bytes.fromhex(d), np.uint8).astype(
                    np.int32
                ) for d in rows]
            )

        return allgather

    def test_agreement_passes(self):
        from tensorflow_examples_tpu.sharding import (
            verify_digest_agreement,
        )

        verify_digest_agreement(
            self.DIGEST_A,
            allgather=self._gather([self.DIGEST_A] * 4),
            process_index=0,
            process_count=4,
        )

    def test_single_process_never_gathers(self):
        from tensorflow_examples_tpu.sharding import (
            verify_digest_agreement,
        )

        def boom(vec):
            raise AssertionError("collective entered on 1 process")

        verify_digest_agreement(
            self.DIGEST_A, allgather=boom, process_count=1
        )

    def test_mismatch_names_the_host(self):
        from tensorflow_examples_tpu.sharding import (
            ShardingMismatchError,
            verify_digest_agreement,
        )

        rows = [self.DIGEST_A, self.DIGEST_A, self.DIGEST_B,
                self.DIGEST_A]
        with pytest.raises(ShardingMismatchError) as ei:
            verify_digest_agreement(
                self.DIGEST_A,
                allgather=self._gather(rows),
                process_index=0,
                process_count=4,
            )
        msg = str(ei.value)
        assert "host 2" in msg and self.DIGEST_B in msg
        assert self.DIGEST_A in msg  # both digests shown
        assert "host 1" not in msg  # agreeing hosts are not accused


class TestShardedTraining:
    def test_2d_mesh_matches_1device_loss_trajectory(self):
        """THE tentpole training claim: 2x2 and 4x2 (data, model) GSPMD
        layouts reproduce the 1-device loss trajectory (f32
        reduction-order tolerance), driven end-to-end by the
        serializable config."""
        cfg = tiny_cfg()
        ref = run_steps(
            make_trainer(cfg, ShardingConfig(mesh={"data": 1})), cfg, 3
        )
        for mesh in ({"data": 2, "model": 2}, {"data": 4, "model": 2}):
            got = run_steps(
                make_trainer(cfg, gpt2_sharding(mesh)), cfg, 3
            )
            # f32 reduction-order deltas compound through the optimizer
            # (~1e-3 relative by step 3 on CPU XLA); 3e-3 relative keeps
            # the parity claim while tolerating summation order.
            np.testing.assert_allclose(
                got, ref, rtol=3e-3, atol=0,
                err_msg=f"mesh {mesh} diverged from 1-device trajectory",
            )

    def test_params_actually_sharded_over_model(self):
        cfg = tiny_cfg()
        trainer = make_trainer(cfg, gpt2_sharding({"data": 2, "model": 2}))
        qkv = trainer.state.params["h_0"]["attn"]["qkv"]["kernel"]
        assert "model" in str(qkv.sharding.spec)
        shard = qkv.addressable_shards[0].data
        assert shard.shape[2] == qkv.shape[2] // 2  # heads dim split

    def test_zero1_quarters_per_device_opt_bytes(self):
        """Acceptance: ZeRO-1 on an 8-way batch mesh drops measured
        per-device optimizer bytes to ≤ 1/4 of the replicated
        baseline (actually ~1/8 — the moments shard 8 ways)."""
        cfg = tiny_cfg()
        base = make_trainer(cfg, gpt2_sharding({"data": 8}))
        z1 = make_trainer(cfg, gpt2_sharding({"data": 8}, zero1=True))
        repl = base.state.byte_breakdown(per_device=True)["opt_state"]
        shrd = z1.state.byte_breakdown(per_device=True)["opt_state"]
        assert repl == base.state.byte_breakdown()["opt_state"]
        assert shrd <= repl / 4, (shrd, repl)
        # Global bytes unchanged — only placement moved.
        assert (
            z1.state.byte_breakdown()["opt_state"]
            == base.state.byte_breakdown()["opt_state"]
        )

    def test_zero1_step_matches_replicated(self):
        cfg = tiny_cfg()
        ref = run_steps(make_trainer(cfg, gpt2_sharding({"data": 8})),
                        cfg, 2)
        got = run_steps(
            make_trainer(cfg, gpt2_sharding({"data": 8}, zero1=True)),
            cfg, 2,
        )
        np.testing.assert_allclose(got, ref, rtol=1e-6)


# ---------------------------------------- fit integration + provenance


@pytest.fixture(scope="module")
def sharded_fit(tmp_path_factory):
    """One 2x2 GPT-2 fit with a workdir, shared by the provenance/
    telemetry/report assertions below (compiles are the cost)."""
    import jax

    wd = str(tmp_path_factory.mktemp("sharded_fit"))
    cfg = tiny_cfg(
        train_steps=2, log_every=1, checkpoint_every=2, workdir=wd
    )
    sc = gpt2_sharding({"data": 2, "model": 2})
    trainer = make_trainer(cfg, sc)
    rng = np.random.RandomState(1)

    def data(start=0):
        while True:
            yield {
                "tokens": rng.randint(
                    0, cfg.vocab_size,
                    size=(cfg.global_batch_size, cfg.seq_len + 1),
                ).astype(np.int32)
            }

    trainer.fit(data())
    del jax
    return wd, cfg, sc, trainer


class TestFitProvenance:
    def test_zero_post_warmup_recompiles(self, sharded_fit):
        """The CI smoke (ISSUE 7 satellite): a 2x2 CPU-mesh GPT-2 fit
        emits zero post-warmup recompiles under the sentinel."""
        _, _, _, trainer = sharded_fit
        assert trainer.sentinel.post_warmup_recompiles() == 0

    def test_sharding_json_persisted(self, sharded_fit):
        wd, _, sc, trainer = sharded_fit
        loaded, extra = ShardingConfig.load_with_extra(
            os.path.join(wd, "sharding.json")
        )
        assert loaded == trainer.sharding
        assert extra["param_sharding_digest"] == trainer.sharding_digest()
        assert extra["mesh_shape"]["data"] == 2
        assert extra["mesh_shape"]["model"] == 2

    def test_final_line_carries_sharding(self, sharded_fit):
        wd, _, _, trainer = sharded_fit
        path = os.path.join(wd, "telemetry", "metrics.jsonl")
        lines = [json.loads(l) for l in open(path)]
        for line in lines:
            assert schema.validate_line(line) == [], line
        finals = [l for l in lines if l["kind"] == "final"]
        assert finals and "sharding" in finals[-1]
        sh = finals[-1]["sharding"]
        assert sh["mesh_shape"] == {
            "data": 2, "fsdp": 1, "model": 2, "context": 1, "pipe": 1
        }
        assert sh["param_sharding_digest"] == trainer.sharding_digest()
        # Non-final lines never carry it (schema v5 contract).
        assert all("sharding" not in l for l in lines if l["kind"] != "final")

    def test_report_renders_mesh_and_digest(self, sharded_fit):
        wd, _, _, trainer = sharded_fit
        import telemetry_report

        record, skipped, _ = telemetry_report.build_record(wd)
        assert skipped == 0
        assert record["mesh_shape"]["model"] == 2
        assert record["param_sharding_digest"] == trainer.sharding_digest()
        # Nontrivial model axis -> the sharded_step_time gate key.
        assert record["sharded_step_time"] == record["step_time_p50"]
        text = telemetry_report.render(record, 0)
        assert "sharding: mesh" in text
        assert trainer.sharding_digest() in text

    def test_resume_same_rules_is_clean(self, sharded_fit):
        """A second fit in the same workdir (same config) passes the
        digest check and restores."""
        wd, cfg, sc, _ = sharded_fit
        trainer = make_trainer(
            cfg.replace(train_steps=2), sc
        )
        rng = np.random.RandomState(2)

        def data(start=0):
            while True:
                yield {
                    "tokens": rng.randint(
                        0, cfg.vocab_size,
                        size=(cfg.global_batch_size, cfg.seq_len + 1),
                    ).astype(np.int32)
                }

        trainer.fit(data())  # restores step 2, loop body is a no-op
        assert int(trainer.state.step) == 2

    def test_drifted_rules_fail_with_named_error(self, sharded_fit):
        wd, cfg, _, _ = sharded_fit
        from jax.sharding import PartitionSpec as P

        drifted = ShardingConfig(
            mesh={"data": 2, "model": 2},
            rules=rules_to_json(transformer.GPT2_RULES)
            + [["wte/embedding", spec_to_json(P("model", None))]],
        )
        trainer = make_trainer(cfg, drifted)
        with pytest.raises(ShardingMismatchError, match="wte/embedding"):
            trainer.fit(iter([]))


# -------------------------------------------- checkpoint resharding


class TestCheckpointResharding:
    def test_bitwise_restore_across_mesh_shapes(self, tmp_path):
        """Acceptance: save on an 8-device (2,4) mesh, restore on 1
        device AND on a (4,2) layout — params bitwise-identical."""
        import jax

        from tensorflow_examples_tpu.train.checkpoint import (
            CheckpointManager,
        )

        cfg = tiny_cfg()
        src = make_trainer(cfg, gpt2_sharding({"data": 2, "model": 4}))
        run_steps(src, cfg, 2)  # real moments, not init zeros
        wd = str(tmp_path)
        with CheckpointManager(wd, async_save=False) as ckpt:
            ckpt.save(2, src.state)
        want = {
            "/".join(str(getattr(p, "key", p)) for p in path): np.asarray(
                leaf
            )
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                src.state.params
            )[0]
        }

        for mesh in ({"data": 1}, {"data": 4, "model": 2}):
            dst = make_trainer(cfg, gpt2_sharding(mesh))
            with CheckpointManager(wd, async_save=False) as ckpt:
                restored, step = ckpt.restore_latest(dst.state)
            assert step == 2
            got = jax.tree_util.tree_flatten_with_path(restored.params)[0]
            for path, leaf in got:
                key = "/".join(
                    str(getattr(p, "key", p)) for p in path
                )
                np.testing.assert_array_equal(
                    np.asarray(leaf), want[key], err_msg=f"{mesh} {key}"
                )
            # Restored INTO the destination layout, not the source's.
            qkv = restored.params["h_0"]["attn"]["qkv"]["kernel"]
            n_model = mesh.get("model", 1)
            assert (
                qkv.sharding.shard_shape(qkv.shape)[2]
                == qkv.shape[2] // max(n_model, 1)
            )

        # The restore-only consumers' path (generate/serve CLIs):
        # a shardings-free eval_shape template must restore a
        # SHARDED-saved checkpoint onto the default device.
        import jax as _jax

        from tensorflow_examples_tpu.train.loop import state_factory

        make_state, _ = state_factory(
            gpt2.make_task(cfg), cfg
        )
        abstract = _jax.eval_shape(make_state, _jax.random.PRNGKey(0))
        with CheckpointManager(wd, async_save=False) as ckpt:
            restored, step = ckpt.restore_latest(abstract)
        assert step == 2
        got = np.asarray(
            restored.params["h_0"]["attn"]["qkv"]["kernel"]
        )
        np.testing.assert_array_equal(got, want["h_0/attn/qkv/kernel"])


# ------------------------------------------------------ sharded serving


@pytest.mark.serving
class TestShardedServing:
    def _engine(self, sc=None, **serve_kw):
        import jax

        from tensorflow_examples_tpu.serving.engine import (
            InferenceEngine,
            ServeConfig,
        )

        mcfg = transformer.TransformerConfig(
            vocab_size=211, max_len=64, num_layers=2, num_heads=2,
            d_model=32, dropout=0.0, attention="xla",
        )
        model = transformer.Transformer(mcfg)
        params = model.init(
            {"params": jax.random.PRNGKey(0)},
            np.zeros((1, 8), np.int32),
        )["params"]
        serve = ServeConfig(
            max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
            **serve_kw,
        )
        return InferenceEngine(mcfg, params, cfg=serve, sharding=sc)

    def test_sharded_params_and_pool(self):
        eng = self._engine(gpt2_sharding({"data": 1, "model": 2}))
        qkv = eng.params["h_0"]["attn"]["qkv"]["kernel"]
        assert "model" in str(qkv.sharding.spec)  # NOT replicated
        assert len({s.device for s in qkv.addressable_shards}) == 2
        assert "model" in str(eng.pool.k.sharding.spec)
        assert eng.param_sharding_digest is not None
        # reallocate() preserves the pool placement.
        old_spec = eng.pool.k.sharding.spec
        eng.pool.reallocate()
        assert eng.pool.k.sharding.spec == old_spec

    def test_batched_token_identity_and_zero_recompiles(self):
        """Acceptance: serving from sharded (non-replicated) params
        keeps batched output token-identical to the unbatched reference
        and zero post-warmup recompiles — through the continuous
        batcher, mixed lengths and sampling settings."""
        from tensorflow_examples_tpu.serving.batcher import (
            ContinuousBatcher,
            Request,
        )

        eng = self._engine(gpt2_sharding({"data": 1, "model": 2}))
        eng.warmup()
        assert eng.warmed
        reqs = [
            Request(prompt=[7], max_new_tokens=5, seed=3),
            Request(prompt=[1, 2, 3, 4, 5], max_new_tokens=6, seed=11,
                    temperature=0.9, top_k=13),
            Request(prompt=list(range(1, 20)), max_new_tokens=4, seed=5,
                    temperature=0.7),
            Request(prompt=[9, 8, 7], max_new_tokens=6, seed=21),
            Request(prompt=list(range(40, 2, -1)), max_new_tokens=5,
                    seed=8, temperature=1.1, top_k=7),
            Request(prompt=[3, 1], max_new_tokens=6, seed=13),
        ]
        batcher = ContinuousBatcher(eng).start()
        try:
            futures = [batcher.submit(r) for r in reqs]
            got = [f.result(timeout=120).tokens for f in futures]
        finally:
            batcher.close()
        for r, tokens in zip(reqs, got):
            ref = eng.reference_generate(
                r.prompt, max_new=r.max_new_tokens, seed=r.seed,
                temperature=r.temperature, top_k=r.top_k,
            )
            assert tokens == ref, (r.prompt, tokens, ref)
        assert eng.post_warmup_recompiles() == 0

    def test_sharded_matches_replicated_engine(self):
        """Placement must not change tokens: the sharded engine's
        greedy output equals the replicated engine's."""
        a = self._engine(gpt2_sharding({"data": 1, "model": 2}))
        b = self._engine(None)
        for eng in (a, b):
            eng.warmup()

        def drive(eng):
            slot = eng.pool.alloc()
            tok, _ = eng.prefill(slot, [5, 4, 3], seed=2)
            out = [tok]
            for _ in range(4):
                out.append(eng.decode([(slot, out[-1], 2, 0.0, 0)])[slot])
            eng.pool.free(slot)
            return out

        assert drive(a) == drive(b)


# ------------------------------------------- quantized x sharded (ISSUE 15)


@pytest.mark.serving
class TestQuantizedShardedServing:
    """The precision registry composes with the ShardingConfig: the
    quantized payload shards by the weight's rule, its per-row scale
    inherits the weight's spec (rank-clipped), and a 2x2-mesh int8
    GPT-2 serves with the same divergence contract as an unsharded
    one — at <= 0.35x the f32 sharded baseline's per-device bytes."""

    def _engine(self, *, weight_dtype, mesh={"data": 2, "model": 2}):
        import jax

        from tensorflow_examples_tpu.serving.engine import (
            InferenceEngine,
            ServeConfig,
        )

        mcfg = transformer.TransformerConfig(
            vocab_size=211, max_len=64, num_layers=2, num_heads=2,
            d_model=32, dropout=0.0, attention="xla",
        )
        model = transformer.Transformer(mcfg)
        params = model.init(
            {"params": jax.random.PRNGKey(0)},
            np.zeros((1, 8), np.int32),
        )["params"]
        return InferenceEngine(
            mcfg, params,
            cfg=ServeConfig(
                max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
                weight_dtype=weight_dtype,
            ),
            sharding=gpt2_sharding(mesh),
        )

    def test_clip_is_scale_only_bad_rules_still_fail_loudly(self):
        """Rank clipping exists FOR quantization scales; an over-ranked
        spec on any other leaf must keep failing at placement — a
        typo'd rules table must not silently re-place a bias."""
        import jax
        from jax.sharding import PartitionSpec as P

        from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
        from tensorflow_examples_tpu.core.sharding import (
            ShardingRules,
            shardings_for_params,
        )

        mesh = create_mesh(MeshConfig(data=4, model=2))
        tree = {"mlp_fc": {"bias": np.zeros((8,), np.float32)}}
        rules = ShardingRules([(r"bias", P("data", "model"))])
        sh = shardings_for_params(tree, mesh, rules)
        with pytest.raises(ValueError):
            jax.device_put(tree, sh)
        # LayerNorm params are also literally named 'scale' — the clip
        # keys on the QuantizedWeight child's key TYPE, so a bad rule
        # on ln scale keeps the loud failure too.
        ln = {"ln_1": {"scale": np.ones((8,), np.float32)}}
        ln_rules = ShardingRules([(r"ln_1/scale", P("data", "model"))])
        with pytest.raises(ValueError):
            jax.device_put(
                ln, shardings_for_params(ln, mesh, ln_rules)
            )

    def test_anchored_rules_still_match_quantized_leaves(self):
        """Quantization extends leaf paths (.../kernel -> .../kernel/q
        + /scale); rules resolve against the WEIGHT's path, so an
        ANCHORED pattern like 'kernel$' keeps sharding a quantized
        weight instead of silently replicating it."""
        import jax
        from jax.sharding import PartitionSpec as P

        from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
        from tensorflow_examples_tpu.core.precision import (
            PrecisionConfig,
            quantize_tree,
        )
        from tensorflow_examples_tpu.core.sharding import (
            ShardingRules,
            shardings_for_params,
        )

        mesh = create_mesh(MeshConfig(data=4, model=2))
        tree = quantize_tree(
            {"mlp_fc": {"kernel": np.ones((8, 16), np.float32)}},
            PrecisionConfig.weight_only("int8"),
        )
        rules = ShardingRules([(r"mlp_fc/kernel$", P(None, "model"))])
        placed = jax.device_put(
            tree, shardings_for_params(tree, mesh, rules)
        )
        qw = placed["mlp_fc"]["kernel"]
        assert "model" in str(qw.q.sharding.spec), (
            "anchored rule must still shard the quantized payload"
        )
        # The scale [8] clips the weight's spec to P(None): replicated
        # here, but resolved THROUGH the weight's rule, not a no-match.
        assert all(a is None for a in qw.scale.sharding.spec)

    def test_scales_sharded_like_their_weights(self):
        from tensorflow_examples_tpu.core.precision import QuantizedWeight

        eng = self._engine(weight_dtype="int8")
        qkv = eng.params["h_0"]["attn"]["qkv"]["kernel"]
        assert isinstance(qkv, QuantizedWeight)
        # The payload keeps the weight's full spec (heads over model)…
        assert "model" in str(qkv.q.sharding.spec)
        assert len({s.device for s in qkv.q.addressable_shards}) >= 2
        # …and the scale [d, 3, H] carries the spec's leading dims —
        # the head axis survives the rank clip, so the scale splits
        # over `model` exactly where its weight does.
        assert "model" in str(qkv.scale.sharding.spec)
        assert len(qkv.scale.sharding.spec) == qkv.scale.ndim
        # Replicated-by-rule leaves (embeddings) stay replicated.
        wte = eng.params["wte"]["embedding"]
        assert isinstance(wte, QuantizedWeight)
        assert all(a is None for a in wte.q.sharding.spec)

    @pytest.mark.timeout(300)
    def test_golden_bytes_and_zero_recompiles(self):
        """The satellite acceptance in one run: batcher golden
        first-token-exact vs the f32 sharded twin with bounded stream
        divergence, zero post-warmup recompiles, and per-device param
        bytes <= 0.35x the f32 sharded baseline via
        byte_breakdown(per_device=True)."""
        from tensorflow_examples_tpu.serving.batcher import (
            ContinuousBatcher,
            Request,
        )

        f32 = self._engine(weight_dtype="")
        quant = self._engine(weight_dtype="int8")
        bb_q = quant.byte_breakdown(per_device=True)
        bb_f = f32.byte_breakdown(per_device=True)
        assert bb_q["params_bytes"] <= 0.35 * bb_f["params_bytes"]
        # The per-device view reports only per-device-meaningful
        # fields — no silently-global numbers to mis-ratio against.
        assert "params_bytes_f32" not in bb_q
        assert "kv_cache_bytes" not in bb_q
        for eng in (f32, quant):
            eng.warmup()
        reqs = [
            Request(prompt=[7], max_new_tokens=5, seed=3),
            Request(prompt=[1, 2, 3, 4, 5], max_new_tokens=6, seed=11,
                    temperature=0.9, top_k=13),
            Request(prompt=list(range(1, 20)), max_new_tokens=4, seed=5),
            Request(prompt=list(range(40, 2, -1)), max_new_tokens=5,
                    seed=8),
        ]
        batcher = ContinuousBatcher(quant).start()
        try:
            futures = [batcher.submit(r) for r in reqs]
            got = [f.result(timeout=120).tokens for f in futures]
        finally:
            batcher.close()
        for r, tokens in zip(reqs, got):
            own = quant.reference_generate(
                r.prompt, max_new=r.max_new_tokens, seed=r.seed,
                temperature=r.temperature, top_k=r.top_k,
            )
            assert tokens == own, "batched != quantized reference"
            ref = f32.reference_generate(
                r.prompt, max_new=r.max_new_tokens, seed=r.seed,
                temperature=r.temperature, top_k=r.top_k,
            )
            assert tokens[0] == ref[0], "first token must be exact"
            agree = sum(a == b for a, b in zip(tokens, ref))
            assert agree >= 0.75 * len(ref), (tokens, ref)
        assert quant.post_warmup_recompiles() == 0

    def test_sharded_quantized_matches_replicated_quantized(self):
        """Quantization happens on the host BEFORE placement, so the
        sharded tree holds the same values — placement still never
        changes tokens, quantized or not."""
        from tensorflow_examples_tpu.serving.engine import (
            InferenceEngine,
            ServeConfig,
        )

        sharded = self._engine(weight_dtype="int8")
        mcfg = sharded.model_cfg
        import jax

        model = transformer.Transformer(mcfg)
        params = model.init(
            {"params": jax.random.PRNGKey(0)},
            np.zeros((1, 8), np.int32),
        )["params"]
        replicated = InferenceEngine(
            mcfg, params,
            cfg=ServeConfig(
                max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
                weight_dtype="int8",
            ),
        )
        for eng in (sharded, replicated):
            eng.warmup()

        def drive(eng):
            slot = eng.pool.alloc()
            tok, _ = eng.prefill(slot, [5, 4, 3], seed=2)
            out = [tok]
            for _ in range(4):
                out.append(eng.decode([(slot, out[-1], 2, 0.0, 0)])[slot])
            eng.pool.free(slot)
            return out

        assert drive(sharded) == drive(replicated)


# ------------------------------------------------------------- schema v5


class TestSchemaV5:
    def _line(self, **kw):
        base = {
            "schema_version": schema.SCHEMA_VERSION,
            "kind": "final",
            "host": 0,
            "step": 10,
            "time_unix": 2.0,
            "session_start_unix": 1.0,
            "metrics": {},
            "counters": {},
            "gauges": {},
            "derived": {},
            "exit_reason": "complete",
            "sharding": {
                "mesh_shape": {"data": 2, "model": 2},
                "param_sharding_digest": "ab12cd34",
                "zero1": False,
            },
        }
        base.update(kw)
        return base

    def test_final_line_with_sharding_validates(self):
        assert schema.validate_line(self._line()) == []

    def test_sharding_on_non_final_rejected(self):
        bad = self._line(kind="window")
        del bad["exit_reason"]
        assert any(
            "non-final" in p for p in schema.validate_line(bad)
        )

    def test_sharding_on_v3_line_rejected(self):
        assert any(
            "v5 field" in p
            for p in schema.validate_line(self._line(schema_version=3))
        )

    def test_sharding_shape_checked(self):
        bad = self._line()
        bad["sharding"] = {"mesh_shape": {"data": 0}}
        problems = schema.validate_line(bad)
        assert any("positive int" in p for p in problems)
        assert any("param_sharding_digest" in p for p in problems)


# ----------------------------------------------------------- tools


class TestShardViz:
    ARGS = [
        "--workload", "gpt2",
        "--set", "num_layers=2", "--set", "d_model=32",
        "--set", "num_heads=4", "--set", "vocab_size=64",
        "--set", "seq_len=16",
    ]

    def test_table_and_totals(self, capsys):
        import shard_viz

        rc = shard_viz.main(
            ["--mesh", "data=2,model=2", "--zero1"] + self.ARGS
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "h_0/attn/qkv/kernel" in out
        assert "replicated" in out and "model" in out
        assert "param sharding digest:" in out
        assert "x reduction" in out  # zero1 opt-state summary

    def test_json_output_matches_resolve(self, capsys):
        import shard_viz

        rc = shard_viz.main(
            ["--mesh", "data=2,model=2", "--json"] + self.ARGS
        )
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["mesh_shape"]["model"] == 2
        rows = {r["path"]: r for r in doc["rows"]}
        qkv = rows["h_0/attn/qkv/kernel"]
        assert not qkv["replicated"]
        assert qkv["per_device_bytes"] == qkv["global_bytes"] // 2
        assert rows["wte/embedding"]["replicated"]
        totals = doc["totals"]
        assert totals["per_device_bytes"] < totals["global_bytes"]

    def test_loads_a_persisted_config(self, tmp_path, capsys):
        import shard_viz

        path = str(tmp_path / "sharding.json")
        gpt2_sharding({"data": 2, "model": 2}).save(path)
        rc = shard_viz.main(["--config", path] + self.ARGS)
        assert rc == 0
        assert "mesh:" in capsys.readouterr().out

    def test_bad_field_named(self):
        import shard_viz

        with pytest.raises(ValueError, match="no such field"):
            shard_viz.main(
                ["--mesh", "data=2", "--workload", "gpt2",
                 "--set", "nope=1"]
            )


class TestBenchGateShardedStepTime:
    def test_stamp_and_gate(self, tmp_path, capsys):
        import bench_gate

        record = {
            "step_time_p50": 0.01,
            "sharded_step_time": 0.012,
            "goodput": 1.0,
        }
        rec_path = str(tmp_path / "record.json")
        floors_path = str(tmp_path / "floors.json")
        with open(rec_path, "w") as f:
            json.dump(record, f)
        assert bench_gate.main(
            ["--stamp", rec_path, "--floors", floors_path]
        ) == 0
        floors = json.load(open(floors_path))
        assert floors["sharded_step_time"] == {"max": 0.012}
        # Same record gates green...
        assert bench_gate.main(
            ["--record", rec_path, "--floors", floors_path]
        ) == 0
        # ...a 50% sharded-step-time regression gates red.
        record["sharded_step_time"] = 0.018
        with open(rec_path, "w") as f:
            json.dump(record, f)
        assert bench_gate.main(
            ["--record", rec_path, "--floors", floors_path]
        ) == 1
        out = capsys.readouterr().out
        assert "sharded_step_time" in out
