"""Distributed-tracing units (ISSUE 18): the trace-context wire
round-trip, span adoption from replica replies, the tail sampler's
forced-keep/slow/seeded decisions, merge-on-finish stitching (the
dedupe/takeover join), the flush-per-line v13 trace sink and its
torn-tail-tolerant reader, exemplar bookkeeping, and the schema-v13
ritual pin (kind="trace" and the v13 serving keys forbidden on every
version that predates them).

Everything here is device-free and O(ms) — the stitched-trace chaos
golden lives in tests/test_chaos.py, the CI smoke in tests/test_tools.
"""

import json

import pytest

from tensorflow_examples_tpu.telemetry import schema, tracing
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry
from tensorflow_examples_tpu.telemetry.tracing import (
    ExemplarStore,
    TraceContext,
    TraceRecorder,
    close_span,
    make_span,
    read_traces,
)

pytestmark = pytest.mark.serving


def _recorder(tmp_path=None, **kw):
    kw.setdefault("registry", MetricsRegistry())
    if tmp_path is not None:
        kw.setdefault("path", str(tmp_path / "traces.jsonl"))
    kw.setdefault("sample_fraction", 0.0)
    return TraceRecorder(**kw)


class TestTraceContext:
    def test_wire_round_trip(self):
        ctx = TraceContext("a" * 16, "b" * 8, sampled=True)
        wire = ctx.to_wire()
        assert wire == {
            "trace_id": "a" * 16, "parent_span_id": "b" * 8,
            "sampled": True,
        }
        back = TraceContext.from_wire(json.loads(json.dumps(wire)))
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id
        assert back.sampled is True

    def test_child_reparents_same_trace(self):
        ctx = TraceContext("t" * 16, "p" * 8)
        kid = ctx.child("c" * 8)
        assert kid.trace_id == ctx.trace_id
        assert kid.span_id == "c" * 8

    @pytest.mark.parametrize("wire", [
        None, 7, "tid", [], {}, {"trace_id": 3},
        {"trace_id": ""}, {"trace_id": "t", "parent_span_id": 9},
    ])
    def test_malformed_wire_is_rejected_not_raised(self, wire):
        assert TraceContext.from_wire(wire) is None

    def test_missing_parent_gets_fresh_span_id(self):
        ctx = TraceContext.from_wire({"trace_id": "t" * 16})
        assert ctx is not None
        assert isinstance(ctx.span_id, str) and ctx.span_id

    def test_ids_are_hex_and_distinct(self):
        tids = {tracing.new_trace_id() for _ in range(64)}
        sids = {tracing.new_span_id() for _ in range(64)}
        assert len(tids) == 64 and len(sids) == 64
        for t in tids:
            int(t, 16)
        for s in sids:
            int(s, 16)


class TestSpanHelpers:
    def test_make_span_shape(self):
        sp = make_span("leg", start_unix=5.0, dur_s=0.25,
                       parent_id="p", tags={"status": 200})
        assert sp["name"] == "leg"
        assert sp["parent_id"] == "p"
        assert sp["start_unix"] == 5.0 and sp["dur_s"] == 0.25
        assert sp["tags"] == {"status": 200}
        assert isinstance(sp["span_id"], str)

    def test_make_span_omits_empty_tags(self):
        assert "tags" not in make_span("x", start_unix=0.0, dur_s=0.0)

    def test_close_span_backdates_start(self):
        import time

        t0 = time.monotonic()
        sp = close_span("work", t0)
        assert sp["dur_s"] >= 0.0
        # start_unix + dur_s lands at "now" (back-dated start).
        assert abs((sp["start_unix"] + sp["dur_s"]) - time.time()) < 1.0


class TestExemplarStore:
    def test_worst_is_max_with_trace_id(self):
        store = ExemplarStore(keep=4)
        store.record("serving/ttft_s", 0.1, "t1")
        store.record("serving/ttft_s", 0.9, "t2")
        store.record("serving/ttft_s", 0.5, "t3")
        assert store.worst()["serving/ttft_s"] == (0.9, "t2")

    def test_ring_is_bounded_and_evicts_old_worst(self):
        store = ExemplarStore(keep=2)
        store.record("m", 9.0, "old")
        store.record("m", 1.0, "a")
        store.record("m", 2.0, "b")
        assert store.worst()["m"] == (2.0, "b")

    def test_empty_store(self):
        assert ExemplarStore().worst() == {}


class TestTailSampling:
    def test_sampled_out_when_boring(self):
        rec = _recorder()
        ctx = rec.new_context()
        doc = rec.finish(ctx.trace_id, e2e_s=0.01)
        assert doc["kept"] is False
        assert doc["keep_reason"] == "sampled_out"

    @pytest.mark.parametrize("flag", [
        "error", "failover", "retried", "hedged", "preempted",
        "deduped", "resumed", "brownout",
    ])
    def test_forced_keep_flags(self, flag):
        rec = _recorder()
        ctx = rec.new_context()
        doc = rec.finish(ctx.trace_id, flags=[flag])
        assert doc["kept"] is True
        assert doc["keep_reason"] == flag

    def test_non_200_status_forces_error_keep(self):
        rec = _recorder()
        ctx = rec.new_context()
        doc = rec.finish(ctx.trace_id, status=503)
        assert doc["kept"] is True and doc["keep_reason"] == "error"

    def test_slow_threshold_is_per_class(self):
        rec = _recorder(slow_s={"interactive": 0.5, "batch": 10.0})
        fast = rec.finish(rec.new_context().trace_id,
                          slo="interactive", e2e_s=0.4)
        slow = rec.finish(rec.new_context().trace_id,
                          slo="interactive", e2e_s=0.6)
        batch = rec.finish(rec.new_context().trace_id,
                           slo="batch", e2e_s=0.6)
        assert fast["kept"] is False
        assert slow["kept"] is True and slow["keep_reason"] == "slow"
        assert batch["kept"] is False

    def test_preempted_span_tag_forces_keep(self):
        rec = _recorder()
        ctx = rec.new_context()
        rec.add_span(ctx.trace_id, make_span(
            "decode", start_unix=0.0, dur_s=0.1,
            tags={"preempted": True}))
        doc = rec.finish(ctx.trace_id)
        assert doc["kept"] is True and doc["keep_reason"] == "preempted"

    def test_seeded_fraction_is_deterministic(self):
        a = _recorder(sample_fraction=0.5, seed=7)
        b = _recorder(sample_fraction=0.5, seed=7)
        ids = [tracing.new_trace_id() for _ in range(64)]
        kept_a = {t for t in ids if a.finish(t)["kept"]}
        kept_b = {t for t in ids if b.finish(t)["kept"]}
        assert kept_a == kept_b
        assert 0 < len(kept_a) < len(ids)
        for t in kept_a:
            assert a.get(t)["keep_reason"] == "seeded"

    def test_fraction_one_keeps_everything(self):
        rec = _recorder(sample_fraction=1.0)
        doc = rec.finish(rec.new_context().trace_id)
        assert doc["kept"] is True and doc["keep_reason"] == "seeded"

    def test_stats_tracks_coverage_and_slow(self):
        rec = _recorder(slow_s={"interactive": 0.5})
        rec.finish(rec.new_context().trace_id, e2e_s=0.01)
        rec.finish(rec.new_context().trace_id, e2e_s=0.9)
        rec.finish(rec.new_context().trace_id, flags=["failover"])
        stats = rec.stats()
        assert stats["traces_kept"] == 2
        assert stats["traces_dropped"] == 1
        assert stats["trace_coverage"] == pytest.approx(2 / 3)
        assert stats["slow_trace_count"] == 1


class TestRecorderSpans:
    def test_span_contextmanager_records_outcome_tags(self):
        rec = _recorder()
        ctx = rec.new_context()
        with rec.span(ctx.trace_id, "dispatch",
                      parent_id=ctx.span_id) as sp:
            sp["tags"]["status"] = 200
        doc = rec.finish(ctx.trace_id, flags=["retried"])
        (span,) = doc["spans"]
        assert span["name"] == "dispatch"
        assert span["parent_id"] == ctx.span_id
        assert span["tags"]["status"] == 200
        assert span["dur_s"] >= 0.0

    def test_ingest_parents_orphans_under_dispatch_span(self):
        rec = _recorder()
        ctx = rec.new_context()
        replica_spans = [
            {"span_id": "aa", "parent_id": None, "name": "queue_wait",
             "start_unix": 1.0, "dur_s": 0.1},
            {"span_id": "bb", "parent_id": "aa", "name": "prefill",
             "start_unix": 1.1, "dur_s": 0.2, "tags": {"chunks": 2}},
        ]
        n = rec.ingest(ctx.trace_id,
                       json.loads(json.dumps(replica_spans)),
                       parent_id="dispatch0")
        assert n == 2
        doc = rec.finish(ctx.trace_id, flags=["retried"])
        by_id = {s["span_id"]: s for s in doc["spans"]}
        assert by_id["aa"]["parent_id"] == "dispatch0"
        assert by_id["bb"]["parent_id"] == "aa"
        assert by_id["bb"]["tags"] == {"chunks": 2}

    def test_ingest_tolerates_garbage(self):
        rec = _recorder()
        ctx = rec.new_context()
        bad = [7, "x", {}, {"span_id": "a", "name": "n"},
               {"span_id": "a", "name": "n", "start_unix": "z",
                "dur_s": 0.0}, None]
        assert rec.ingest(ctx.trace_id, bad) == 0
        assert rec.ingest(ctx.trace_id, "not-a-list") == 0

    def test_span_cap_counts_overflow(self):
        reg = MetricsRegistry()
        rec = _recorder(registry=reg, max_spans=3)
        ctx = rec.new_context()
        for i in range(5):
            rec.add_span(ctx.trace_id, make_span(
                f"s{i}", start_unix=float(i), dur_s=0.0))
        doc = rec.finish(ctx.trace_id, flags=["retried"])
        assert len(doc["spans"]) == 3
        assert doc["spans_dropped"] == 2
        assert reg.counter_values()["trace/spans_dropped_total"] == 2

    def test_get_open_then_finished(self):
        rec = _recorder()
        ctx = rec.new_context()
        rec.add_span(ctx.trace_id, make_span(
            "queue", start_unix=0.0, dur_s=0.1))
        open_doc = rec.get(ctx.trace_id)
        assert open_doc["open"] is True
        assert len(open_doc["spans"]) == 1
        rec.finish(ctx.trace_id, flags=["retried"])
        done = rec.get(ctx.trace_id)
        assert "open" not in done and done["kept"] is True
        assert rec.get("nope") is None

    def test_done_lru_is_bounded(self):
        rec = _recorder(keep_traces=2)
        tids = [rec.new_context().trace_id for _ in range(3)]
        for t in tids:
            rec.finish(t, flags=["retried"])
        assert rec.get(tids[0]) is None
        assert rec.get(tids[1]) is not None
        assert rec.get(tids[2]) is not None


class TestMergeOnFinish:
    def test_second_finish_stitches_spans(self):
        """The takeover/dedupe join: finishing an already-finished
        trace_id merges span sets instead of forking the tree."""
        rec = _recorder()
        t = rec.new_context().trace_id
        rec.add_span(t, make_span("request", start_unix=1.0, dur_s=1.0,
                                  span_id="root"))
        rec.finish(t, e2e_s=1.0, flags=["failover"])
        # Same trace_id arrives again (dedupe hit on a successor).
        rec.new_context({"trace_id": t})
        rec.add_span(t, make_span("dedupe_hit", start_unix=2.0,
                                  dur_s=0.01, span_id="dd"))
        doc = rec.finish(t, e2e_s=0.01, flags=["deduped"])
        names = [s["name"] for s in doc["spans"]]
        assert names == ["request", "dedupe_hit"]
        assert set(doc["flags"]) >= {"failover", "deduped"}
        assert doc["e2e_s"] == 1.0
        assert doc["kept"] is True

    def test_merge_dedupes_span_ids(self):
        rec = _recorder()
        t = rec.new_context().trace_id
        rec.add_span(t, make_span("request", start_unix=1.0, dur_s=1.0,
                                  span_id="root"))
        rec.finish(t, flags=["retried"])
        rec.new_context({"trace_id": t})
        rec.add_span(t, make_span("request", start_unix=1.0, dur_s=1.0,
                                  span_id="root"))
        doc = rec.finish(t, flags=["retried"])
        assert len(doc["spans"]) == 1

    def test_kept_survives_a_sampled_out_second_finish(self):
        rec = _recorder()
        t = rec.new_context().trace_id
        rec.finish(t, flags=["failover"])
        rec.new_context({"trace_id": t})
        doc = rec.finish(t)
        assert doc["kept"] is True
        assert doc["keep_reason"] == "failover"

    def test_error_status_sticks_through_merge(self):
        rec = _recorder()
        t = rec.new_context().trace_id
        rec.finish(t, status=504)
        rec.new_context({"trace_id": t})
        doc = rec.finish(t, status=200)
        assert doc["status"] == 504


class TestTraceSink:
    def test_kept_traces_land_as_valid_v13_lines(self, tmp_path):
        rec = _recorder(tmp_path)
        ctx = rec.new_context()
        rec.add_span(ctx.trace_id, make_span(
            "request", start_unix=1.0, dur_s=0.5, tags={"slo": "i"}))
        rec.finish(ctx.trace_id, e2e_s=0.5, flags=["failover"])
        rec.finish(rec.new_context().trace_id)  # sampled out: no line
        rec.close()
        lines = [json.loads(x) for x in
                 open(tmp_path / "traces.jsonl") if x.strip()]
        assert len(lines) == 1
        (line,) = lines
        assert line["schema_version"] == 14
        assert line["kind"] == "trace"
        assert schema.validate_line(line) == []
        assert line["trace"]["trace_id"] == ctx.trace_id
        assert line["trace"]["keep_reason"] == "failover"
        assert "kept" not in line["trace"]

    def test_read_traces_merges_and_tolerates_torn_tail(self, tmp_path):
        rec = _recorder(tmp_path)
        t = rec.new_context().trace_id
        rec.add_span(t, make_span("request", start_unix=1.0, dur_s=1.0,
                                  span_id="root"))
        rec.finish(t, e2e_s=1.0, flags=["failover"])
        # A successor router writes its OWN line for the same trace
        # (separate recorder, same file — the takeover shape).
        rec2 = TraceRecorder(registry=MetricsRegistry(),
                             path=str(tmp_path / "traces.jsonl"),
                             sample_fraction=0.0)
        rec2.new_context({"trace_id": t})
        rec2.add_span(t, make_span("dedupe_hit", start_unix=2.0,
                                   dur_s=0.01, span_id="dd"))
        rec2.finish(t, e2e_s=0.01, flags=["deduped"])
        rec.close()
        rec2.close()
        with open(tmp_path / "traces.jsonl", "a") as f:
            f.write('{"kind": "trace", "torn')  # crash-torn tail
        merged = read_traces(str(tmp_path / "traces.jsonl"))
        assert set(merged) == {t}
        names = [s["name"] for s in merged[t]["spans"]]
        assert names == ["request", "dedupe_hit"]
        assert merged[t]["e2e_s"] == 1.0

    def test_read_traces_missing_file(self, tmp_path):
        assert read_traces(str(tmp_path / "absent.jsonl")) == {}


class TestSchemaV13Ritual:
    """The versioning ritual: the v13 additions exist, and both the
    kind and the serving keys are forbidden on every line that
    predates them."""

    def test_v13_pins(self):
        assert schema.SERVING_SCHEMA_VERSION == 14  # v14: ISSUE 19
        assert schema.SERVING_KEYS_V13 == (
            "traces_kept", "traces_dropped", "trace_coverage",
            "slow_trace_count",
        )
        assert schema.KINDS_V12 == schema.KINDS_V3 + ("serving",)
        assert schema.KINDS_V13 == schema.KINDS_V12 + ("trace",)
        assert schema.KINDS == schema.KINDS_V13 + ("alert",)
        assert "trace/" in schema.INSTRUMENT_PREFIXES

    def _trace_line(self, **over):
        line = {
            "schema_version": 13, "kind": "trace", "step": 0,
            "time_unix": 2.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {}, "counters": {}, "gauges": {}, "derived": {},
            "trace": {
                "trace_id": "t" * 16, "slo": "interactive",
                "status": 200, "e2e_s": 0.5, "keep_reason": "slow",
                "spans": [
                    {"span_id": "a", "parent_id": None,
                     "name": "request", "start_unix": 1.5,
                     "dur_s": 0.5},
                    {"span_id": "b", "parent_id": "a",
                     "name": "dispatch", "start_unix": 1.6,
                     "dur_s": 0.4, "tags": {"status": 200}},
                ],
            },
        }
        line.update(over)
        return line

    def test_valid_trace_line_passes(self):
        assert schema.validate_line(self._trace_line()) == []

    def test_trace_kind_forbidden_before_v13(self):
        for version in (4, 5, 6, 7, 8, 9, 10, 11, 12):
            problems = schema.validate_line(
                self._trace_line(schema_version=version))
            assert any("kind 'trace'" in p for p in problems), (
                version, problems)

    def test_v13_serving_keys_forbidden_before_v13(self):
        base = {
            "schema_version": 13, "kind": "serving", "step": 1,
            "time_unix": 1.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {}, "counters": {}, "gauges": {}, "derived": {},
            "serving": {
                "active_requests": 0, "queue_depth": 0, "slots": 4,
                "kv_occupancy": 0.0, "post_warmup_recompiles": 0,
                "draining": 0, "traces_kept": 2, "traces_dropped": 1,
                "trace_coverage": 0.66, "slow_trace_count": 1,
            },
        }
        assert schema.validate_line(base) == []
        for version in (4, 5, 6, 7, 8, 9, 10, 11, 12):
            stale = dict(base, schema_version=version)
            problems = schema.validate_line(stale)
            for key in schema.SERVING_KEYS_V13:
                assert any(
                    f"v13 serving key '{key}'" in p for p in problems
                ), (version, key, problems)

    def test_trace_object_forbidden_on_non_trace_lines(self):
        line = self._trace_line(kind="window")
        line["metrics"] = {"loss": 1.0}
        problems = schema.validate_line(line)
        assert any("trace object on a non-trace line" in p
                   for p in problems)

    def test_missing_trace_object_flagged(self):
        line = self._trace_line()
        del line["trace"]
        problems = schema.validate_line(line)
        assert any("missing the trace object" in p for p in problems)

    def test_span_shape_enforced(self):
        line = self._trace_line()
        line["trace"]["spans"] = [
            {"span_id": 7, "name": "x", "start_unix": 1.0,
             "dur_s": "z", "parent_id": 3, "tags": []},
            {"name": "y"},
            "not-a-span",
        ]
        problems = schema.validate_line(line)
        blob = "\n".join(problems)
        assert "['span_id'] = 7 is not a string" in blob
        assert "['dur_s'] = 'z' is not a number" in blob
        assert "['parent_id'] = 3 is not a string or null" in blob
        assert "['tags'] = [] is not an object" in blob
        assert "missing 'span_id'" in blob
        assert "trace['spans'][2] is not an object" in blob

    def test_status_bool_rejected(self):
        line = self._trace_line()
        line["trace"]["status"] = True
        problems = schema.validate_line(line)
        assert any("is not an int" in p for p in problems)
