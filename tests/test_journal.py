"""Control-plane durability units (ISSUE 16): the request journal's
crash-safe JSONL contract (torn tail, invalid lines, dedupe window,
tail-follow), the lease's monotonic fencing token (stale heartbeats
refused, atomic replace), the standby monitor's promote path over a
device-free fake fleet, and the schema-v12 ritual pin (v12 serving
keys forbidden on v4–v11).

Everything here is device-free and socket-light — the real-engine
takeover golden lives in tests/test_chaos.py; this tier proves each
mechanism in isolation at O(ms).
"""

import json
import os
import threading

import pytest

from tensorflow_examples_tpu.serving.journal import (
    JOURNAL_VERSION,
    Lease,
    RequestJournal,
    StandbyMonitor,
    validate_record,
)
from tensorflow_examples_tpu.serving.router import Router, RouterConfig
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

pytestmark = pytest.mark.serving


def _intent_body(prompt=(5, 6), seed=0, **over):
    body = {
        "prompt": list(prompt), "max_new_tokens": 3,
        "temperature": 0.0, "top_k": 0, "seed": seed,
    }
    body.update(over)
    return body


class TestValidateRecord:
    def test_valid_records_pass(self, tmp_path):
        j = RequestJournal(str(tmp_path / "j.jsonl"))
        intent = j.append_intent("r1", _intent_body())
        progress = j.append_progress("r1", 2)
        done = j.append_done("r1", [7, 8, 9], 200)
        for rec in (intent, progress, done):
            assert validate_record(rec) == []
        j.close()

    def test_not_an_object(self):
        assert validate_record([1, 2]) == ["record is not an object"]

    def test_wrong_version_and_unknown_kind(self):
        problems = validate_record({"v": 99, "rec": "nope"})
        assert any("journal version" in p for p in problems)
        assert any("unknown record kind" in p for p in problems)

    def test_missing_fields_named(self):
        problems = validate_record(
            {"v": JOURNAL_VERSION, "rec": "intent", "request_id": "r"}
        )
        assert any("missing 'prompt'" in p for p in problems)
        assert any("missing 'seed'" in p for p in problems)

    def test_typed_fields(self):
        bad_prompt = {
            "v": JOURNAL_VERSION, "rec": "intent", "request_id": "r",
            "prompt": [1, True], "max_new_tokens": 4,
            "temperature": 0.0, "top_k": 0, "seed": 0,
            "slo": "interactive", "tenant": "default", "ts": 1.0,
        }
        assert any(
            "token ids" in p for p in validate_record(bad_prompt)
        )
        bad_progress = {
            "v": JOURNAL_VERSION, "rec": "progress", "request_id": "r",
            "committed": "2", "ts": 1.0,
        }
        assert any(
            "int offset" in p for p in validate_record(bad_progress)
        )
        bad_done = {
            "v": JOURNAL_VERSION, "rec": "done", "request_id": "r",
            "tokens": 7, "status": "200", "ts": 1.0,
        }
        problems = validate_record(bad_done)
        assert any("tokens must be a list" in p for p in problems)
        assert any("status must be an int" in p for p in problems)

    def test_empty_request_id_rejected(self):
        rec = {
            "v": JOURNAL_VERSION, "rec": "progress", "request_id": "",
            "committed": 1, "ts": 1.0,
        }
        assert any(
            "non-empty string" in p for p in validate_record(rec)
        )


class TestRequestJournal:
    def test_append_lookup_incomplete_roundtrip(self, tmp_path):
        j = RequestJournal(str(tmp_path / "j.jsonl"))
        j.append_intent("r1", _intent_body(seed=3))
        assert j.has_intent("r1") and not j.has_intent("r2")
        assert [i["request_id"] for i in j.incomplete()] == ["r1"]
        j.append_progress("r1", 1)
        j.append_progress("r1", 2)
        assert j.committed("r1") == 2
        j.append_done("r1", [6, 7, 8], 200)
        assert j.incomplete() == []
        hit = j.lookup("r1")
        assert hit["tokens"] == [6, 7, 8] and hit["status"] == 200
        assert j.lookup("never") is None
        st = j.stats()
        assert st["appends"] == 4 and st["incomplete"] == 0
        assert st["done"] == 1 and st["torn_tail"] == 0
        j.close()

    def test_progress_watermark_is_monotonic(self, tmp_path):
        j = RequestJournal(str(tmp_path / "j.jsonl"))
        j.append_intent("r1", _intent_body())
        j.append_progress("r1", 5)
        j.append_progress("r1", 2)  # stale replayed offset
        assert j.committed("r1") == 5
        j.close()

    def test_fresh_reader_replays_file(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        w = RequestJournal(path)
        w.append_intent("r1", _intent_body())
        w.append_intent("r2", _intent_body(seed=1))
        w.append_done("r1", [9], 200)
        w.close()
        r = RequestJournal(path)  # __init__ refreshes
        assert [i["request_id"] for i in r.incomplete()] == ["r2"]
        assert r.lookup("r1")["tokens"] == [9]
        r.close()

    def test_tail_follow_between_instances(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        w = RequestJournal(path)
        r = RequestJournal(path)
        w.append_intent("r1", _intent_body())
        assert r.refresh() == 1 and r.has_intent("r1")
        # The writer's own appends are pre-applied: refresh is a no-op.
        assert w.refresh() == 0
        w.append_done("r1", [4], 200)
        assert r.refresh() == 1 and r.incomplete() == []
        w.close()
        r.close()

    def test_torn_tail_tolerated_not_consumed(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        w = RequestJournal(path)
        full = w.append_intent("r1", _intent_body())
        w.close()
        # Simulate the writer dying mid-append: a valid line then half
        # of the next one, no terminating newline.
        frag = json.dumps(dict(full, request_id="r2"))
        with open(path, "ab") as f:
            f.write(frag[: len(frag) // 2].encode())
        r = RequestJournal(path)
        assert r.has_intent("r1") and not r.has_intent("r2")
        assert r.stats()["torn_tail"] == 1
        assert r.stats()["invalid_lines"] == 0
        # The writer was merely slow: once the line completes, the next
        # refresh applies it from the held-back offset.
        with open(path, "ab") as f:
            f.write((frag[len(frag) // 2:] + "\n").encode())
        assert r.refresh() == 1 and r.has_intent("r2")
        r.close()

    def test_append_after_torn_tail_not_welded(self, tmp_path):
        """A successor writer appending after a crash-torn tail must
        terminate the dead writer's fragment first: welding the new
        record onto the fragment would merge them into ONE invalid
        line, silently discarding the new record for every reader."""
        path = str(tmp_path / "j.jsonl")
        w = RequestJournal(path)
        full = w.append_intent("r1", _intent_body())
        w.close()
        # The old writer died mid-append: half a line, no newline.
        frag = json.dumps(dict(full, request_id="r2"))
        with open(path, "ab") as f:
            f.write(frag[: len(frag) // 2].encode())
        successor = RequestJournal(path)
        successor.append_intent("r3", _intent_body(seed=9))
        assert successor.stats()["torn_tail_repaired"] == 1
        assert successor.has_intent("r3")
        successor.close()
        # Every fresh reader sees the successor's record intact; the
        # dead writer's fragment is one complete invalid line, counted
        # and never applied.
        reader = RequestJournal(path)
        assert reader.has_intent("r1") and reader.has_intent("r3")
        assert not reader.has_intent("r2")
        st = reader.stats()
        assert st["invalid_lines"] == 1 and st["torn_tail"] == 0
        reader.close()

    def test_clean_tail_append_repairs_nothing(self, tmp_path):
        """The repair path only fires on a torn tail: reopening a
        cleanly-closed journal appends without touching the file."""
        path = str(tmp_path / "j.jsonl")
        w = RequestJournal(path)
        w.append_intent("r1", _intent_body())
        w.close()
        again = RequestJournal(path)
        again.append_intent("r2", _intent_body(seed=1))
        assert again.stats()["torn_tail_repaired"] == 0
        again.close()
        reader = RequestJournal(path)
        assert reader.stats()["invalid_lines"] == 0
        assert reader.has_intent("r1") and reader.has_intent("r2")
        reader.close()

    def test_torn_tail_counted_once_per_fragment(self, tmp_path):
        """One crash artifact = one count: a fragment that persists
        across poll ticks (the standby refreshes every 0.25s) must not
        inflate the stat once per refresh."""
        path = str(tmp_path / "j.jsonl")
        w = RequestJournal(path)
        full = w.append_intent("r1", _intent_body())
        w.close()
        frag = json.dumps(dict(full, request_id="r2"))
        with open(path, "ab") as f:
            f.write(frag[:10].encode())
        r = RequestJournal(path)
        for _ in range(5):
            r.refresh()
        assert r.stats()["torn_tail"] == 1
        # A merely-slow writer growing the SAME fragment in place is
        # still the same single torn tail.
        with open(path, "ab") as f:
            f.write(frag[10:20].encode())
        r.refresh()
        assert r.stats()["torn_tail"] == 1
        # Completing the line consumes it; a NEW fragment at a new
        # offset is a second artifact.
        with open(path, "ab") as f:
            f.write((frag[20:] + "\n").encode())
        assert r.refresh() == 1 and r.has_intent("r2")
        with open(path, "ab") as f:
            f.write(b'{"half')
        r.refresh()
        assert r.stats()["torn_tail"] == 2
        r.close()

    def test_invalid_lines_counted_not_applied(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        with open(path, "w") as f:
            f.write("this is not json\n")
            f.write(json.dumps({"rec": "intent", "v": 0}) + "\n")
        j = RequestJournal(path)
        assert j.stats()["invalid_lines"] == 2
        assert j.incomplete() == []
        j.close()

    def test_append_refuses_invalid_record(self, tmp_path):
        j = RequestJournal(str(tmp_path / "j.jsonl"))
        with pytest.raises(ValueError, match="invalid journal record"):
            j.append_intent("r1", {"prompt": []})
        j.close()

    def test_dedupe_window_evicts_oldest(self, tmp_path):
        j = RequestJournal(str(tmp_path / "j.jsonl"), dedup_window=2)
        for i in range(3):
            rid = f"r{i}"
            j.append_intent(rid, _intent_body(seed=i))
            j.append_done(rid, [i], 200)
        assert j.lookup("r0") is None  # evicted from the window
        assert j.lookup("r1") and j.lookup("r2")
        st = j.stats()
        assert st["dedup_evictions"] == 1 and st["dedup_entries"] == 2
        # Eviction only forgets the TOKENS: completion is remembered,
        # so an evicted id never re-enters the replay worklist.
        assert j.incomplete() == []
        j.close()

    def test_counter_stamped_per_append(self, tmp_path):
        reg = MetricsRegistry()
        j = RequestJournal(str(tmp_path / "j.jsonl"), registry=reg)
        j.append_intent("r1", _intent_body())
        j.append_done("r1", [1], 200)
        assert reg.counter("router/journal_appends_total").value == 2
        j.close()

    def test_concurrent_appends_all_land(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = RequestJournal(path)

        def work(k):
            for i in range(10):
                j.append_intent(f"r{k}-{i}", _intent_body(seed=i))

        threads = [
            threading.Thread(target=work, args=(k,)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        j.close()
        fresh = RequestJournal(path)
        assert len(fresh.incomplete()) == 40
        assert fresh.stats()["invalid_lines"] == 0
        fresh.close()


class TestLease:
    def test_acquire_is_monotonic(self, tmp_path):
        lease = Lease(str(tmp_path / "l.json"), owner="a")
        assert lease.acquire() == 1
        assert lease.acquire() == 2
        cur = lease.read()
        assert cur["token"] == 2 and cur["owner"] == "a"

    def test_missing_or_garbage_file_reads_none(self, tmp_path):
        lease = Lease(str(tmp_path / "l.json"))
        assert lease.read() is None and lease.age_s() is None
        with open(lease.path, "w") as f:
            f.write("not json")
        assert lease.read() is None

    def test_stale_heartbeat_refused_and_never_clobbers(self, tmp_path):
        path = str(tmp_path / "l.json")
        old = Lease(path, owner="primary")
        t1 = old.acquire()
        new = Lease(path, owner="standby")
        t2 = new.acquire()
        before = new.read()
        assert old.heartbeat(t1) is False  # fenced: no write
        assert new.read() == before
        assert new.heartbeat(t2) is True
        assert new.read()["ts"] >= before["ts"]

    def test_fenced_is_strictly_newer_token(self, tmp_path):
        lease = Lease(str(tmp_path / "l.json"))
        t1 = lease.acquire()
        assert not lease.fenced(t1)
        t2 = lease.acquire()
        assert lease.fenced(t1) and not lease.fenced(t2)
        # Token 0 (the standby's pre-promotion token) is fenced by ANY
        # granted lease — standby passivity is the same check.
        assert lease.fenced(0)

    def test_heartbeat_resets_age(self, tmp_path):
        lease = Lease(str(tmp_path / "l.json"))
        token = lease.acquire()
        assert lease.age_s() is not None and lease.age_s() >= 0.0
        assert lease.heartbeat(token)
        assert lease.age_s() < 5.0

    def test_atomic_write_leaves_no_tmp(self, tmp_path):
        lease = Lease(str(tmp_path / "l.json"))
        lease.acquire()
        assert [p.name for p in tmp_path.glob("*.tmp.*")] == []

    def test_concurrent_acquires_across_instances_stay_monotonic(
        self, tmp_path
    ):
        """Separate Lease INSTANCES (each with its own threading.Lock —
        the shape two router PROCESSES have) racing acquires: the
        sidecar flock serializes the read-modify-write, so every grant
        is a unique, strictly increasing token. Without it a revived
        primary's heartbeat could read its old token, pass the check,
        and clobber a standby's newer lease — reverting the fence."""
        path = str(tmp_path / "l.json")
        tokens = []
        tlock = threading.Lock()

        def work(owner):
            lease = Lease(path, owner=owner)
            for _ in range(10):
                t = lease.acquire()
                with tlock:
                    tokens.append(t)

        threads = [
            threading.Thread(target=work, args=(f"r{k}",))
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # 40 serialized read-modify-writes: tokens 1..40, no duplicate
        # (a duplicate = two holders believing they own the lease).
        assert sorted(tokens) == list(range(1, 41))
        assert os.path.exists(path + ".lock")


class TestStandbyMonitor:
    """Promotion mechanics over a replica-less router — the fleet side
    is the chaos tier's job; here only the lease/journal choreography
    is under test, driven by poll_once() for determinism."""

    def _standby(self, tmp_path, **kw):
        journal = RequestJournal(str(tmp_path / "j.jsonl"))
        lease = Lease(str(tmp_path / "l.json"), owner="primary")
        # One unreachable replica: the router requires a non-empty
        # fleet, and a refused connect makes the promote-time sweep
        # instant. Fleet behaviour itself is the chaos tier's job.
        router = Router(
            ["http://127.0.0.1:9"],
            cfg=RouterConfig(probe_interval_s=30.0),
            journal=journal,
        )
        monitor = StandbyMonitor(
            router, lease=lease, journal=journal,
            miss_budget_s=kw.pop("miss_budget_s", 0.05), **kw
        )
        return journal, lease, router, monitor

    def test_fenced_until_promoted_then_takes_over(self, tmp_path):
        journal, lease, router, monitor = self._standby(tmp_path)
        try:
            lease.acquire()  # the primary's grant
            assert router.fenced()  # standby holds token 0
            monitor.poll_once()  # heartbeat fresh: no promotion yet
            assert not monitor.promoted.is_set()
            import time as _time

            _time.sleep(0.06)  # blow the miss budget
            monitor.poll_once()
            assert monitor.promoted.is_set()
            assert not router.fenced()
            assert monitor.takeover_latency_s is not None
            reg = router.registry
            assert reg.counter("router/takeover_total").value == 1
            assert (
                reg.gauge("router/takeover_latency_s").value
                == monitor.takeover_latency_s
            )
        finally:
            monitor.close()
            router.close()
            journal.close()

    def test_no_lease_means_no_promotion(self, tmp_path):
        journal, lease, router, monitor = self._standby(tmp_path)
        try:
            monitor.poll_once()  # age_s() is None: nothing to miss
            assert not monitor.promoted.is_set()
        finally:
            monitor.close()
            router.close()
            journal.close()

    def test_promote_is_idempotent(self, tmp_path):
        journal, lease, router, monitor = self._standby(tmp_path)
        try:
            monitor.promote()
            token = lease.read()["token"]
            monitor.promote()
            monitor.poll_once()
            assert lease.read()["token"] == token
            assert (
                router.registry.counter("router/takeover_total").value
                == 1
            )
        finally:
            monitor.close()
            router.close()
            journal.close()


class TestKillRouterCountsGeneratesOnly:
    """``killrouter@T`` is specified in GENERATE dispatches: mixed
    classify/score traffic must not advance T, or a chaos run kills
    the router earlier than the fault spec says."""

    def test_classify_never_advances_the_kill_count(
        self, serve_faults, tmp_path
    ):
        engine = serve_faults("killrouter@1")
        router = Router(
            ["http://127.0.0.1:9"],
            cfg=RouterConfig(
                probe_interval_s=30.0, retry_budget_s=0.2,
                max_retries=0, retry_backoff_s=0.01,
                # Keep breaker/health ejection out of this test: the
                # unreachable replica must stay nominally eligible so
                # dispatches reach the fault hook, not the fast-fail.
                eject_after=100, unhealthy_after=100,
            ),
        )
        try:
            # Classify dispatches reach the (unreachable) fleet and
            # fail there — the router-kill hook never sees them.
            for _ in range(3):
                status, body = router.handle(
                    {"prompt": [1, 2]}, kind="classify"
                )
                assert status == 503
                assert "router killed" not in body.get("error", "")
            assert not any(
                k == "killrouter" for k, _, _ in engine.fired
            )
            # The first GENERATE dispatch is the one that fires it.
            status, body = router.handle(
                {"prompt": [1, 2], "max_new_tokens": 2},
                kind="generate",
            )
            assert status == 503
            assert "router killed" in body.get("error", "")
            assert any(k == "killrouter" for k, _, _ in engine.fired)
        finally:
            router.close()


class TestSchemaV12:
    """The schema ritual (ISSUE 16 satellite): v12 keys exist, are
    forbidden on every version that predates them, and the journal-less
    router's line still validates."""

    def test_v12_key_tuple_pinned(self):
        assert schema.SERVING_SCHEMA_VERSION == 14
        assert schema.SERVING_KEYS_V12 == (
            "journal_appends", "takeover_total", "resumed_streams",
            "dedup_hits", "takeover_latency_s",
        )

    def test_v12_keys_flagged_on_older_versions(self):
        base = {
            "schema_version": 12, "kind": "serving", "step": 1,
            "time_unix": 1.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {}, "counters": {}, "gauges": {}, "derived": {},
            "serving": {
                "active_requests": 0, "queue_depth": 0, "slots": 4,
                "kv_occupancy": 0.0, "post_warmup_recompiles": 0,
                "draining": 0, "journal_appends": 3,
                "takeover_total": 1, "resumed_streams": 2,
                "dedup_hits": 4, "takeover_latency_s": 0.25,
            },
        }
        assert schema.validate_line(base) == []
        for version in (4, 5, 6, 7, 8, 9, 10, 11):
            stale = dict(base, schema_version=version)
            problems = schema.validate_line(stale)
            for key in schema.SERVING_KEYS_V12:
                assert any(
                    f"v12 serving key '{key}'" in p for p in problems
                ), (version, key, problems)

    def test_router_line_carries_v12_keys(self, tmp_path):
        journal = RequestJournal(str(tmp_path / "j.jsonl"))
        router = Router(["http://127.0.0.1:9"], journal=journal)
        try:
            line = json.loads(json.dumps(router.stats_line()))
            assert line["schema_version"] == 14
            assert schema.validate_line(line) == []
            for key in schema.SERVING_KEYS_V12:
                assert key in line["serving"], key
        finally:
            router.close()
            journal.close()

    def test_journal_less_router_line_validates(self):
        router = Router(["http://127.0.0.1:9"])
        try:
            line = json.loads(json.dumps(router.stats_line()))
            assert line["schema_version"] == 14
            assert schema.validate_line(line) == []
        finally:
            router.close()
