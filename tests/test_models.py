"""Model-builder unit tests: shapes and parameter counts (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def n_params(tree):
    return sum(x.size for x in jax.tree.leaves(tree))


class TestResNet20:
    @pytest.fixture(scope="class")
    def model_and_vars(self):
        from tensorflow_examples_tpu.models.resnet import resnet20

        model = resnet20(num_classes=10)
        variables = model.init(
            {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 32, 32, 3))
        )
        return model, variables

    def test_param_count(self, model_and_vars):
        # Canonical ResNet-20 (He et al.) is ~0.27M params.
        _, variables = model_and_vars
        count = n_params(variables["params"])
        assert 0.26e6 < count < 0.29e6, count

    def test_forward_shape_and_finite(self, model_and_vars):
        model, variables = model_and_vars
        logits = model.apply(variables, jnp.ones((4, 32, 32, 3)), train=False)
        assert logits.shape == (4, 10)
        assert logits.dtype == jnp.float32
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_batch_stats_update_in_train_mode(self, model_and_vars):
        model, variables = model_and_vars
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
        _, new_vars = model.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        before = variables["batch_stats"]["stem_bn"]["mean"]
        after = new_vars["batch_stats"]["stem_bn"]["mean"]
        assert not bool(jnp.allclose(before, after))


class TestResNet50:
    def test_param_count_and_shape(self):
        from tensorflow_examples_tpu.models.resnet import resnet50

        model = resnet50(num_classes=1000)
        variables = jax.eval_shape(
            lambda rng: model.init({"params": rng}, jnp.zeros((1, 224, 224, 3))),
            jax.random.PRNGKey(0),
        )
        # Canonical ResNet-50 is ~25.5M params.
        count = n_params(variables["params"])
        assert 25.0e6 < count < 26.0e6, count

    def test_tiny_forward(self):
        # Full 224x224 init is slow on CPU; a tiny variant with the same
        # builder exercises the bottleneck/stem paths cheaply.
        from tensorflow_examples_tpu.models.resnet import (
            BottleneckBlock,
            ResNet,
        )

        model = ResNet(
            stage_sizes=(1, 1),
            block_cls=BottleneckBlock,
            num_classes=7,
            num_filters=8,
            stem="imagenet",
        )
        variables = model.init(
            {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 64, 64, 3))
        )
        logits = model.apply(variables, jnp.ones((2, 64, 64, 3)), train=False)
        assert logits.shape == (2, 7)


class TestAugment:
    def test_crop_flip_shape_and_determinism(self):
        import numpy as np

        from tensorflow_examples_tpu.data.augment import random_crop_flip

        x = np.random.default_rng(0).normal(size=(16, 32, 32, 3)).astype(np.float32)
        a = random_crop_flip(x, np.random.default_rng(7))
        b = random_crop_flip(x, np.random.default_rng(7))
        c = random_crop_flip(x, np.random.default_rng(8))
        assert a.shape == x.shape
        assert np.array_equal(a, b)  # same rng stream → identical
        assert not np.array_equal(a, c)

    def test_crop_preserves_content_statistics(self):
        import numpy as np

        from tensorflow_examples_tpu.data.augment import random_crop_flip

        x = np.ones((4, 32, 32, 3), np.float32)
        out = random_crop_flip(x, np.random.default_rng(0))
        # Reflect-pad of a constant image is constant → crops identical.
        assert np.allclose(out, 1.0)


def test_resnet_family_param_counts():
    """Canonical torchvision parameter counts certify the architectures."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.models import resnet

    expected = {
        resnet.resnet18: 11_689_512,
        resnet.resnet34: 21_797_672,
        resnet.resnet50: 25_557_032,
        resnet.resnet101: 44_549_160,
        resnet.resnet152: 60_192_808,
    }
    for builder, want in expected.items():
        model = builder(num_classes=1000)
        shapes = jax.eval_shape(
            lambda r, m=model: m.init(
                {"params": r}, jnp.zeros((1, 224, 224, 3), jnp.float32)
            ),
            jax.random.PRNGKey(0),
        )["params"]
        n = sum(np.prod(s.shape) for s in jax.tree.leaves(shapes))
        assert n == want, f"{builder.__name__}: {n} != {want}"
