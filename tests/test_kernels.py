"""Pallas kernel numerics vs pure-XLA references (SURVEY.md §4).

Runs the real kernel code in Pallas interpret mode on CPU; on TPU the
same code path compiles via Mosaic (exercised by bench.py / examples).
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_examples_tpu.ops.attention import (
    attention_reference,
    flash_attention,
)
from tensorflow_examples_tpu.ops.cross_entropy import (
    cross_entropy_loss,
    cross_entropy_per_example,
    cross_entropy_reference,
)
from tensorflow_examples_tpu.ops.decode import (
    decode_attention_reference,
    flash_decode_attention,
)


def _qkv(rng, shape, dtype):
    ks = jax.random.split(rng, 3)
    return [jax.random.normal(k, shape, dtype) for k in ks]


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("seq", [128, 256])
    def test_forward_matches_reference(self, causal, seq):
        q, k, v = _qkv(jax.random.PRNGKey(0), (2, 3, seq, 64), jnp.float32)
        out = flash_attention(q, k, v, causal=causal, block_q=128, block_kv=128)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_forward_bf16(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), (1, 2, 256, 64), jnp.bfloat16)
        out = flash_attention(q, k, v, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            out.astype(np.float32), ref.astype(np.float32), atol=2e-2, rtol=2e-2
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_reference(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(2), (1, 2, 256, 64), jnp.float32)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                a, b, atol=5e-4, rtol=5e-4, err_msg=f"d{name}"
            )

    def test_uneven_blocks(self):
        # seq divisible by blocks but blocks differ; causal offsets exercise
        # the loop-bound math.
        q, k, v = _qkv(jax.random.PRNGKey(3), (1, 1, 256, 64), jnp.float32)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_kv=128)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_cross_attention_lengths(self):
        # seq_q != seq_kv: causal diagonal is bottom-right aligned, like
        # the reference; exercises the offset loop-bound math.
        rng = jax.random.PRNGKey(5)
        q = jax.random.normal(rng, (1, 2, 128, 64))
        k = jax.random.normal(jax.random.PRNGKey(6), (1, 2, 384, 64))
        v = jax.random.normal(jax.random.PRNGKey(7), (1, 2, 384, 64))
        for causal in (True, False):
            out = flash_attention(q, k, v, causal=causal, block_q=64, block_kv=128)
            ref = attention_reference(q, k, v, causal=causal)
            np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
        # Gradients through the offset path too.
        g = jax.grad(
            lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2),
        )(q, k, v)
        gr = jax.grad(
            lambda q, k, v: jnp.sum(attention_reference(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(
                a, b, atol=5e-4, rtol=5e-4, err_msg=f"d{name}"
            )

    def test_jit_compatible(self):
        q, k, v = _qkv(jax.random.PRNGKey(4), (2, 2, 128, 64), jnp.float32)
        jitted = jax.jit(lambda q, k, v: flash_attention(q, k, v))
        np.testing.assert_allclose(
            jitted(q, k, v), flash_attention(q, k, v), atol=1e-6, rtol=1e-6
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_key_bias_matches_reference(self, causal):
        """Padding-mask bias (the BERT shape): [batch, seq_kv] additive,
        broadcast over heads/rows, spanning multiple KV blocks so the
        per-block bias tiles are exercised."""
        q, k, v = _qkv(jax.random.PRNGKey(8), (2, 3, 256, 64), jnp.float32)
        # Batch row 0 masks the last 77 keys; row 1 masks none.
        from tensorflow_examples_tpu.ops.attention import NEG_INF

        kb = np.zeros((2, 256), np.float32)
        kb[0, -77:] = NEG_INF
        kb = jnp.asarray(kb)
        out = flash_attention(
            q, k, v, causal=causal, key_bias=kb, block_q=64, block_kv=64
        )
        ref = attention_reference(q, k, v, causal=causal, key_bias=kb)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_key_bias_gradients(self):
        """Grads wrt q/k/v through the biased kernel must match the
        reference; the bias cotangent is defined as zero (mask data)."""
        q, k, v = _qkv(jax.random.PRNGKey(9), (1, 2, 128, 64), jnp.float32)
        from tensorflow_examples_tpu.ops.attention import NEG_INF

        kb = jnp.asarray(
            np.where(np.arange(128) < 100, 0.0, NEG_INF)[None], jnp.float32
        )

        def loss(f):
            return lambda q, k, v: jnp.sum(
                f(q, k, v) ** 2
            )

        flash = lambda q, k, v: flash_attention(
            q, k, v, causal=False, key_bias=kb
        )
        ref = lambda q, k, v: attention_reference(
            q, k, v, causal=False, key_bias=kb
        )
        g_flash = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss(ref), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                a, b, atol=5e-4, rtol=5e-4, err_msg=f"d{name}"
            )
        # Masked keys must contribute exactly zero dk/dv.
        np.testing.assert_allclose(np.asarray(g_flash[1])[:, :, 100:], 0.0)
        np.testing.assert_allclose(np.asarray(g_flash[2])[:, :, 100:], 0.0)


class TestFlashDecode:
    """KV-cache flash-decode kernel vs the masked-XLA reference."""

    @pytest.mark.parametrize(
        "q_len,length",
        [(1, 1), (1, 13), (1, 512), (7, 200), (128, 128), (96, 300)],
    )
    def test_matches_reference(self, q_len, length):
        rng = jax.random.PRNGKey(0)
        q = jax.random.normal(rng, (2, 3, q_len, 64))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 512, 64))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 3, 512, 64))
        out = flash_decode_attention(q, k, v, jnp.asarray(length))
        ref = decode_attention_reference(q, k, v, length)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_garbage_cache_tail_ignored(self):
        """Slots ≥ length must not affect the output (they hold stale or
        uninitialized data in real decode)."""
        q = jax.random.normal(jax.random.PRNGKey(3), (1, 2, 1, 64))
        k = jax.random.normal(jax.random.PRNGKey(4), (1, 2, 256, 64))
        v = jax.random.normal(jax.random.PRNGKey(5), (1, 2, 256, 64))
        out = flash_decode_attention(q, k, v, jnp.asarray(100))
        k2 = k.at[:, :, 100:].set(1e4)
        v2 = v.at[:, :, 100:].set(-1e4)
        out2 = flash_decode_attention(q, k2, v2, jnp.asarray(100))
        np.testing.assert_allclose(out, out2, atol=0, rtol=0)

    def test_overlong_length_clamps_like_traced(self):
        """length > max_len: the static path must clamp to the full
        cache exactly like the traced path's searchsorted clamp (it
        used to raise a bare StopIteration); both must equal the
        full-cache answer."""
        q = jax.random.normal(jax.random.PRNGKey(9), (1, 2, 1, 64))
        k = jax.random.normal(jax.random.PRNGKey(10), (1, 2, 256, 64))
        v = jax.random.normal(jax.random.PRNGKey(11), (1, 2, 256, 64))
        full = decode_attention_reference(q, k, v, 256)
        np.testing.assert_allclose(
            flash_decode_attention(q, k, v, 300), full, atol=2e-5, rtol=2e-5
        )
        np.testing.assert_allclose(
            flash_decode_attention(q, k, v, jnp.asarray(300)),
            full, atol=2e-5, rtol=2e-5,
        )

    def test_jit_traced_length(self):
        """length as a traced scalar: one compile serves every context
        size — the property the generate() scan relies on."""
        q = jax.random.normal(jax.random.PRNGKey(6), (1, 2, 1, 64))
        k = jax.random.normal(jax.random.PRNGKey(7), (1, 2, 256, 64))
        v = jax.random.normal(jax.random.PRNGKey(8), (1, 2, 256, 64))
        f = jax.jit(flash_decode_attention)
        for n in (1, 77, 256):
            np.testing.assert_allclose(
                f(q, k, v, jnp.asarray(n)),
                decode_attention_reference(q, k, v, n),
                atol=2e-5, rtol=2e-5,
            )

    def test_odd_lengths_partial_blocks(self):
        """max_len/q_len without a block divisor (e.g. 4·odd): the cdiv
        grid's padded tail must be fully masked."""
        q = jax.random.normal(jax.random.PRNGKey(9), (1, 2, 36, 64))
        k = jax.random.normal(jax.random.PRNGKey(10), (1, 2, 516, 64))
        v = jax.random.normal(jax.random.PRNGKey(11), (1, 2, 516, 64))
        out = flash_decode_attention(
            q, k, v, jnp.asarray(400), block_q=32, block_kv=256
        )
        ref = decode_attention_reference(q, k, v, 400)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_bucket_ladder_boundaries(self):
        """The power-of-two KV-grid ladder (O(context) sequencing): the
        traced length must pick a sufficient bucket and stay exact at
        and around every bucket boundary, jit'd once for all lengths."""
        q = jax.random.normal(jax.random.PRNGKey(15), (1, 2, 1, 64))
        k = jax.random.normal(jax.random.PRNGKey(16), (1, 2, 1024, 64))
        v = jax.random.normal(jax.random.PRNGKey(17), (1, 2, 1024, 64))
        f = jax.jit(
            functools.partial(flash_decode_attention, block_kv=64)
        )
        for n in (1, 64, 65, 128, 129, 512, 513, 1000, 1024):
            np.testing.assert_allclose(
                f(q, k, v, jnp.asarray(n)),
                decode_attention_reference(q, k, v, n),
                atol=2e-5, rtol=2e-5, err_msg=f"length={n}",
            )

    def test_static_length_single_bucket(self):
        """A Python-int length compiles exactly one bucket, no switch."""
        q = jax.random.normal(jax.random.PRNGKey(18), (1, 2, 1, 64))
        k = jax.random.normal(jax.random.PRNGKey(19), (1, 2, 1024, 64))
        v = jax.random.normal(jax.random.PRNGKey(20), (1, 2, 1024, 64))
        out = flash_decode_attention(q, k, v, 100, block_kv=64)
        ref = decode_attention_reference(q, k, v, 100)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_bf16_cache(self):
        q = jax.random.normal(jax.random.PRNGKey(12), (1, 2, 1, 64), jnp.bfloat16)
        k = jax.random.normal(jax.random.PRNGKey(13), (1, 2, 128, 64), jnp.bfloat16)
        v = jax.random.normal(jax.random.PRNGKey(14), (1, 2, 128, 64), jnp.bfloat16)
        out = flash_decode_attention(q, k, v, jnp.asarray(64))
        ref = decode_attention_reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), 64,
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            out.astype(np.float32), ref, atol=2e-2, rtol=2e-2
        )


class TestPagedDecodeKernel:
    """ISSUE 11 satellite: the fused Pallas paged-decode kernel
    (ops/paged_decode.py) pinned element-wise against the XLA gather
    path (the serving oracle) in interpret mode, across the slot-length
    / block-table edge cases the paged pool actually produces."""

    BS, NB, H, D = 8, 9, 2, 16

    def _pool(self, seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        k = jnp.asarray(
            rng.standard_normal((self.NB, self.H, self.BS, self.D)), dtype
        )
        v = jnp.asarray(
            rng.standard_normal((self.NB, self.H, self.BS, self.D)), dtype
        )
        return k, v

    def _case(self, lengths, tables, seed=0):
        from tensorflow_examples_tpu.ops.paged_decode import (
            paged_decode_attention,
            paged_decode_reference,
        )

        rng = np.random.default_rng(seed + 100)
        s = len(lengths)
        q = jnp.asarray(
            rng.standard_normal((s, self.H, self.D)), jnp.float32
        )
        k, v = self._pool(seed)
        lengths = jnp.asarray(lengths, jnp.int32)
        tables = jnp.asarray(tables, jnp.int32)
        out = paged_decode_attention(q, k, v, lengths, tables)
        ref = paged_decode_reference(q, k, v, lengths, tables)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-6, rtol=2e-6
        )
        return out

    def test_single_block_and_length_one(self):
        self._case([1, 8], [[3, 0], [5, 0]])

    def test_ragged_last_block(self):
        # Lengths ending mid-block: the final block is partially
        # populated and masked, exactly the common decode state.
        self._case([13, 21, 30], [[1, 2, 0, 0], [3, 4, 5, 0],
                                  [6, 7, 8, 2]])

    def test_empty_slot_is_finite_garbage(self):
        # A parked slot (length 0) must come out finite (its output is
        # discarded downstream — both paths emit garbage there, and
        # DIFFERENT garbage: the oracle's all-masked softmax is
        # uniform, the kernel's epsilon-guarded sum is ~0 — so only
        # the populated slot is compared element-wise) and never NaN.
        from tensorflow_examples_tpu.ops.paged_decode import (
            paged_decode_attention,
            paged_decode_reference,
        )

        rng = np.random.default_rng(5)
        q = jnp.asarray(
            rng.standard_normal((2, self.H, self.D)), jnp.float32
        )
        k, v = self._pool(5)
        lengths = jnp.asarray([0, 5], jnp.int32)
        tables = jnp.asarray([[0, 0], [4, 0]], jnp.int32)
        out = paged_decode_attention(q, k, v, lengths, tables)
        ref = paged_decode_reference(q, k, v, lengths, tables)
        assert bool(jnp.all(jnp.isfinite(out)))
        np.testing.assert_allclose(
            np.asarray(out[1]), np.asarray(ref[1]), atol=2e-6, rtol=2e-6
        )

    def test_null_padded_tables_never_leak(self):
        # Two slots share a pool; slot 0's null-padded tail entries
        # must not read slot 1's blocks: perturbing an UNREFERENCED
        # block changes nothing.
        from tensorflow_examples_tpu.ops.paged_decode import (
            paged_decode_attention,
        )

        rng = np.random.default_rng(7)
        q = jnp.asarray(
            rng.standard_normal((1, self.H, self.D)), jnp.float32
        )
        k, v = self._pool(7)
        lengths = jnp.asarray([10], jnp.int32)
        tables = jnp.asarray([[2, 6, 0, 0]], jnp.int32)
        base = paged_decode_attention(q, k, v, lengths, tables)
        k2 = k.at[5].add(100.0)  # block 5 is unreferenced
        v2 = v.at[5].add(100.0)
        again = paged_decode_attention(q, k2, v2, lengths, tables)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(again))

    def test_int8_scales_dequant_in_kernel(self):
        from tensorflow_examples_tpu.core.precision import (
            quantize_int8_rows,
        )
        from tensorflow_examples_tpu.ops.paged_decode import (
            paged_decode_attention,
            paged_decode_reference,
        )

        rng = np.random.default_rng(3)
        s = 3
        q = jnp.asarray(
            rng.standard_normal((s, self.H, self.D)), jnp.float32
        )
        k, v = self._pool(3)
        qk, ks = quantize_int8_rows(k)
        qv, vs = quantize_int8_rows(v)
        lengths = jnp.asarray([5, 16, 27], jnp.int32)
        tables = jnp.asarray(
            [[1, 0, 0, 0], [2, 3, 0, 0], [4, 5, 6, 7]], jnp.int32
        )
        out = paged_decode_attention(
            q, qk, qv, lengths, tables, k_scale=ks, v_scale=vs
        )
        ref = paged_decode_reference(
            q, qk, qv, lengths, tables, k_scale=ks, v_scale=vs
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-6, rtol=2e-6
        )

    def test_jit_traced_lengths_and_tables(self):
        from tensorflow_examples_tpu.ops.paged_decode import (
            paged_decode_attention,
            paged_decode_reference,
        )

        rng = np.random.default_rng(11)
        q = jnp.asarray(
            rng.standard_normal((2, self.H, self.D)), jnp.float32
        )
        k, v = self._pool(11)
        fn = jax.jit(
            lambda *a: paged_decode_attention(*a, interpret=True)
        )
        lengths = jnp.asarray([7, 19], jnp.int32)
        tables = jnp.asarray([[3, 0, 0], [1, 2, 4]], jnp.int32)
        out = fn(q, k, v, lengths, tables)
        ref = paged_decode_reference(q, k, v, lengths, tables)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-6, rtol=2e-6
        )

    def test_scale_pairing_enforced(self):
        from tensorflow_examples_tpu.ops.paged_decode import (
            paged_decode_attention,
        )

        k, v = self._pool()
        with pytest.raises(ValueError, match="both k_scale and v_scale"):
            paged_decode_attention(
                jnp.zeros((1, self.H, self.D)), k, v,
                jnp.ones((1,), jnp.int32),
                jnp.zeros((1, 2), jnp.int32),
                k_scale=jnp.ones((self.NB, self.H, self.BS)),
            )


class TestFusedCrossEntropy:
    @pytest.mark.parametrize("vocab", [1000, 50257])
    def test_forward_matches_reference(self, vocab):
        rng = jax.random.PRNGKey(0)
        logits = jax.random.normal(rng, (64, vocab), jnp.float32) * 3
        labels = jax.random.randint(jax.random.PRNGKey(1), (64,), 0, vocab)
        nll = cross_entropy_per_example(logits, labels, fused=True)
        ref = cross_entropy_reference(logits, labels)
        np.testing.assert_allclose(nll, ref, atol=1e-5, rtol=1e-5)

    def test_gradient_matches_reference(self):
        vocab = 4099  # not divisible by block_v: exercises padding mask
        logits = jax.random.normal(jax.random.PRNGKey(2), (32, vocab))
        labels = jax.random.randint(jax.random.PRNGKey(3), (32,), 0, vocab)

        g_fused = jax.grad(
            lambda l: jnp.mean(cross_entropy_per_example(l, labels, fused=True))
        )(logits)
        g_ref = jax.grad(
            lambda l: jnp.mean(cross_entropy_reference(l, labels))
        )(logits)
        np.testing.assert_allclose(g_fused, g_ref, atol=1e-6, rtol=1e-5)

    def test_loss_weighted_mean_masks_padding(self):
        logits = jax.random.normal(jax.random.PRNGKey(4), (2, 8, 512))
        labels = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 0, 512)
        weights = jnp.ones((2, 8)).at[:, -3:].set(0.0)
        loss = cross_entropy_loss(logits, labels, weights, fused=True)
        ref_rows = cross_entropy_reference(
            logits.reshape(-1, 512), labels.reshape(-1)
        ).reshape(2, 8)
        expected = np.sum(np.asarray(ref_rows) * np.asarray(weights)) / np.sum(
            np.asarray(weights)
        )
        np.testing.assert_allclose(float(loss), expected, rtol=1e-6)

    def test_bf16_logits(self):
        logits = jax.random.normal(
            jax.random.PRNGKey(6), (16, 1024), jnp.bfloat16
        )
        labels = jax.random.randint(jax.random.PRNGKey(7), (16,), 0, 1024)
        nll = cross_entropy_per_example(logits, labels, fused=True)
        ref = cross_entropy_reference(logits.astype(jnp.float32), labels)
        np.testing.assert_allclose(nll, ref, atol=2e-2, rtol=2e-2)

    @pytest.mark.parametrize(
        "b,s",
        [
            (8, 16),  # everything divides: full batch+seq+model sharding
            (1, 16),  # batch 1 on a dp mesh: batch axes dropped
            (8, 7),   # seq indivisible by model/context: seq axes dropped
            (3, 5),   # nothing divides: degenerates to the plain call
        ],
    )
    def test_mesh_ce_matches_plain_across_divisibility(self, b, s):
        """mesh_cross_entropy_per_example must reproduce the unsharded
        NLL for every branch of the shared axis-dropping policy
        (core/mesh.py token_partition_axes) — including the replicated
        fallbacks for decode-time batch=1 and odd seq lengths."""
        from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
        from tensorflow_examples_tpu.ops.cross_entropy import (
            mesh_cross_entropy_per_example,
        )

        vocab = 97
        mesh = create_mesh(MeshConfig(data=2, model=2, context=2))
        logits = jax.random.normal(jax.random.PRNGKey(8), (b, s, vocab))
        labels = jax.random.randint(
            jax.random.PRNGKey(9), (b, s), 0, vocab
        )
        want = cross_entropy_reference(
            logits.reshape(-1, vocab), labels.reshape(-1)
        ).reshape(b, s)
        got = jax.jit(
            functools.partial(mesh_cross_entropy_per_example, mesh=mesh)
        )(logits, labels)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )


def test_block_autofit_odd_lengths():
    """Auto (None) block sizes must fit sequences the 256 target doesn't
    divide, stepping down through hardware-legal (multiple-of-128, then
    multiple-of-8) divisors; pathological lengths raise instead of
    degenerating, and explicit block sizes are enforced, not overridden."""
    import jax
    import pytest

    from tensorflow_examples_tpu.ops.attention import (
        _fit_block,
        _resolve_block,
        attention_reference,
        flash_attention,
    )

    assert _fit_block(256, 384) == 128  # prefers the 128-multiple divisor
    assert _fit_block(256, 320) == 160  # no 128-multiple divides 320; 8-mult
    assert _fit_block(256, 256) == 256
    assert _fit_block(256, 100) == 100  # whole sequence as one block
    with pytest.raises(ValueError):  # 1021 prime: no legal tiling
        _fit_block(256, 1021)
    with pytest.raises(ValueError):  # explicit size that doesn't divide
        _resolve_block(192, 1024)
    for s in (320, 384):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, s, 64))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, s, 64))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, s, 64))
        out = flash_attention(q, k, v, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_tuned_block_table_consulted(tmp_path, monkeypatch):
    """_resolve_block prefers the committed swept table for a matching
    seq and falls back to the 256 target otherwise."""
    import json

    from tensorflow_examples_tpu.ops import attention

    attention._tuned_block_table.cache_clear()
    monkeypatch.setattr(  # monkeypatch restores the lru_cache'd original
        attention, "_tuned_block_table",
        lambda: {"1024": {"block_q": 512, "block_kv": 128}},
    )
    assert attention._resolve_block(None, 1024, "block_q") == 512
    assert attention._resolve_block(None, 1024, "block_kv") == 128
    assert attention._resolve_block(None, 2048, "block_q") == 256
    # explicit sizes still win over the table
    assert attention._resolve_block(128, 1024, "block_q") == 128


def test_tuned_block_table_loader_handles_absent_file():
    from tensorflow_examples_tpu.ops import attention

    attention._tuned_block_table.cache_clear()
    table = attention._tuned_block_table()
    assert isinstance(table, dict)  # {} when no sweep is banked
    attention._tuned_block_table.cache_clear()


def test_flash_table_from_sweep_tool(tmp_path):
    import json
    import subprocess
    import sys as _sys

    sweep = {
        "complete": True,
        "shapes": [
            {"name": "s1024", "batch": 8, "heads": 12, "seq": 1024,
             "head_dim": 64, "causal": True,
             "best_fwd": {"block_q": 256, "block_kv": 256, "fwd_ms": 1.0},
             "best_fwdbwd": {"block_q": 512, "block_kv": 256,
                             "fwdbwd_ms": 3.0}},
        ],
    }
    p = tmp_path / "sweep.json"
    p.write_text(json.dumps(sweep))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    r = subprocess.run(
        [_sys.executable,
         os.path.join(repo, "tools", "flash_table_from_sweep.py"), str(p)],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    table = json.loads((tmp_path / "flash_block_table.json").read_text())
    assert table["by_seq"]["1024"]["block_q"] == 512
    # partial sweep refused
    sweep["complete"] = False
    p.write_text(json.dumps(sweep))
    r = subprocess.run(
        [_sys.executable,
         os.path.join(repo, "tools", "flash_table_from_sweep.py"), str(p)],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 1
