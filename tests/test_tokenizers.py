"""Tokenizers + offline data-prep tools (SURVEY.md §2a rows 4–5).

The reference relied on downloaded tokenizer assets; here both
tokenizers are pure-python and trainable offline, so these tests build
real vocabularies from in-test corpora and assert lossless (BPE) /
faithful (WordPiece) round-trips, then drive the prep tools end-to-end
into the exact formats the data loaders consume.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tensorflow_examples_tpu.data.tokenizers import (
    ByteLevelBPE,
    WordPiece,
    bytes_to_unicode,
)

CORPUS = [
    "The quick brown fox jumps over the lazy dog. "
    "The dog was not amused, the fox was very pleased.\n",
    "Training language models requires tokenized text; tokenizers turn "
    "text into integers and back again without losing information.\n",
    "Numbers like 1234 and 3.14159, punctuation?! And unicode: café, "
    "naïve, 中文, emoji \U0001f680✨.\n",
]


def test_byte_unicode_map_reversible():
    m = bytes_to_unicode()
    assert len(m) == 256
    assert len(set(m.values())) == 256  # bijective


class TestByteLevelBPE:
    @pytest.fixture(scope="class")
    def bpe(self):
        return ByteLevelBPE.train(CORPUS, vocab_size=400)

    def test_roundtrip_lossless(self, bpe):
        for text in CORPUS + [
            "completely unseen text with weird   spacing\t\tand\nnewlines",
            "bytes outside the corpus: üñîçødè \U0001f4af",
            "",
            " leading and trailing ",
        ]:
            ids = bpe.encode(text)
            assert bpe.decode(ids) == text

    def test_merges_actually_compress(self, bpe):
        text = CORPUS[0]
        ids = bpe.encode(text)
        assert len(ids) < len(text.encode("utf-8"))  # better than bytes

    def test_eot_token(self, bpe):
        assert bpe.eot_id == bpe.vocab_size - 1
        assert bpe.decode([bpe.eot_id]) == ""  # specials dropped on decode

    def test_save_load_identical(self, bpe, tmp_path):
        bpe.save(str(tmp_path))
        reloaded = ByteLevelBPE.from_dir(str(tmp_path))
        for text in CORPUS:
            assert reloaded.encode(text) == bpe.encode(text)
        assert reloaded.vocab_size == bpe.vocab_size

    def test_gpt2_file_format(self, tmp_path):
        """Hand-written vocab.json/merges.txt in the published format."""
        vocab = {c: i for i, c in enumerate(map(chr, range(33, 127)))}
        vocab["he"] = len(vocab)
        vocab["hel"] = len(vocab)
        with open(tmp_path / "vocab.json", "w") as f:
            json.dump(vocab, f)
        with open(tmp_path / "merges.txt", "w") as f:
            f.write("#version: 0.2\nh e\nhe l\n")
        tok = ByteLevelBPE.from_dir(str(tmp_path))
        ids = tok.encode("hello")
        assert [tok.decoder[i] for i in ids] == ["hel", "l", "o"]
        assert tok.decode(ids) == "hello"


class TestWordPiece:
    @pytest.fixture(scope="class")
    def wp(self):
        return WordPiece.build(CORPUS, vocab_size=300)

    def test_tokenize_known_words(self, wp):
        pieces = wp.tokenize("The quick fox")
        assert pieces  # non-empty
        rebuilt = wp.decode([wp.vocab[p] for p in pieces])
        assert rebuilt == "the quick fox"  # lowercased, faithful

    def test_subword_fallback(self, wp):
        # Unseen word splits into known subpieces or [UNK], never crashes.
        pieces = wp.tokenize("zzgrxq unbelievabletokenization")
        assert all(p == "[UNK]" or p.lstrip("#") for p in pieces)

    def test_encode_schema(self, wp):
        f = wp.encode("the fox was pleased", "the dog was not", seq_len=32)
        assert f["tokens"].shape == (32,)
        assert f["attention_mask"].shape == (32,)
        assert f["token_type_ids"].shape == (32,)
        n = int(f["attention_mask"].sum())
        assert f["tokens"][0] == wp.vocab["[CLS]"]
        seps = np.where(f["tokens"][:n] == wp.vocab["[SEP]"])[0]
        assert len(seps) == 2  # pair input → two separators
        # Type ids: 0 through the first [SEP], 1 after it.
        assert f["token_type_ids"][seps[0]] == 0
        assert f["token_type_ids"][seps[0] + 1] == 1
        assert (f["tokens"][n:] == wp.vocab["[PAD]"]).all()

    def test_truncation(self, wp):
        long = "fox " * 100
        f = wp.encode(long, long, seq_len=16)
        assert int(f["attention_mask"].sum()) == 16

    def test_vocab_file_roundtrip(self, wp, tmp_path):
        path = str(tmp_path / "vocab.txt")
        wp.save(path)
        reloaded = WordPiece.from_vocab_file(path)
        text = "tokenizers turn text into integers"
        assert reloaded.tokenize(text) == wp.tokenize(text)


# ----------------------------------------------------------------- tools


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_tool(script, *args):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script), *args],
        capture_output=True,
        text=True,
        timeout=300,
        # CPU-only tool: the sitecustomize axon register() can block
        # interpreter start >=90 s while the tunnel is wedged.
        env={k: v for k, v in os.environ.items()
             if k != "PALLAS_AXON_POOL_IPS"},
    )
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


def test_prepare_lm_end_to_end(tmp_path):
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(" ".join(CORPUS) * 30)
    out = tmp_path / "lm"
    _run_tool(
        "prepare_lm.py",
        f"--input={corpus}",
        f"--out_dir={out}",
        "--train_vocab=320",
        "--val_fraction=0.1",
    )
    from tensorflow_examples_tpu.data.sources import load_lm_tokens

    ds = load_lm_tokens(str(out), "train", seq_len=32, vocab_size=320)
    toks = ds.arrays["tokens"]
    assert toks.shape[1] == 33 and toks.shape[0] > 0
    # Decode a window back: must be real corpus text, not garbage.
    tok = ByteLevelBPE.from_dir(str(out))
    text = tok.decode(toks[0])
    assert "fox" in text or "token" in text or "Number" in text
    assert os.path.exists(out / "val.bin")


def test_prepare_glue_end_to_end(tmp_path):
    tsv = tmp_path / "train.tsv"
    rows = ["sentence\tlabel"]
    for i in range(12):
        rows.append(f"this movie was {'great fun' if i % 2 else 'a dull mess'}\t{i % 2}")
    tsv.write_text("\n".join(rows) + "\n")
    out = tmp_path / "glue"
    _run_tool(
        "prepare_glue.py",
        "--task=sst2",
        f"--input={tsv}",
        "--split=train",
        f"--out_dir={out}",
        "--build_vocab=200",
        "--seq_len=24",
    )
    from tensorflow_examples_tpu.data.sources import load_glue

    ds = load_glue(str(out), "sst2", "train", seq_len=24)
    a = ds.arrays
    assert a["tokens"].shape == (12, 24)
    assert a["attention_mask"].shape == (12, 24)
    assert a["token_type_ids"].shape == (12, 24)
    assert set(np.asarray(a["label"]).tolist()) == {0, 1}


def test_prepare_glue_pair_task(tmp_path):
    tsv = tmp_path / "train.tsv"
    rows = ["index\tsentence1\tsentence2\tlabel"]
    for i in range(6):
        rows.append(f"{i}\tthe fox jumped\tthe dog slept\t{'entailment' if i % 2 else 'not_entailment'}")
    tsv.write_text("\n".join(rows) + "\n")
    out = tmp_path / "glue"
    _run_tool(
        "prepare_glue.py",
        "--task=rte",
        f"--input={tsv}",
        "--split=validation",
        f"--out_dir={out}",
        "--build_vocab=150",
        "--seq_len=32",
    )
    d = np.load(out / "rte_validation.npz")
    assert d["token_type_ids"].max() == 1  # pair → second segment present
    assert set(d["label"].tolist()) == {0, 1}


def test_stdlib_re_fallback_pattern_is_lossless():
    """The `re` fallback pre-tokenizer (used only when the `regex`
    package is absent) must still cover every character — underscores
    are the trap: "_" is \\w but not a letter class member."""
    import re

    # Mirror of the fallback pattern in data/tokenizers.py.
    pat = re.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+"
        r"|\s+(?!\S)|\s+",
        re.UNICODE,
    )
    for text in [
        "foo_bar",
        "__init__ = a_1 + b_2",
        "mixed _lead and trail_ cases",
        "the quick brown fox! 42 times?",
    ]:
        assert "".join(pat.findall(text)) == text, text
