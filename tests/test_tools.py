"""Tests for the harvest/stamp tooling (tools/*.py).

These scripts guard the round's on-chip evidence — a parsing or merge
bug silently loses or mislabels TPU records — so their contracts are
pinned here at the same level as the framework code (SURVEY.md §4
test strategy: every layer that can corrupt results gets direct unit
coverage).
"""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from last_json_line import last_json_line  # noqa: E402


def _rec(bench, backend="tpu", value=1.0, **kw):
    r = {
        "metric": f"{bench}_metric", "bench": bench, "value": value,
        "unit": "u", "backend": backend, "window_values": [value],
        "fingerprint_tflops_pre": 100.0, "fingerprint_tflops_post": 110.0,
    }
    r.update(kw)
    return r


class TestLastJsonLine:
    def test_picks_last_parseable(self, tmp_path):
        p = tmp_path / "log"
        p.write_text(
            "noise\n"
            + json.dumps({"a": 1}) + "\n"
            + "{broken json\n"
            + json.dumps({"a": 2}) + "\n"
            + "trailing noise\n"
        )
        assert last_json_line(str(p)) == {"a": 2}

    def test_no_json_and_missing_file(self, tmp_path):
        p = tmp_path / "log"
        p.write_text("nothing here\n")
        assert last_json_line(str(p)) is None
        assert last_json_line(str(tmp_path / "absent")) is None

    def test_cli_requirements(self, tmp_path):
        log = tmp_path / "log"
        out = tmp_path / "out.json"
        log.write_text(json.dumps({"backend": "tpu", "v": 3}) + "\n")
        ok = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "last_json_line.py"),
             str(log), str(out), "backend=tpu"],
            capture_output=True,
        )
        assert ok.returncode == 0
        assert json.load(open(out))["v"] == 3
        bad = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "last_json_line.py"),
             str(log), str(out), "backend=cpu"],
            capture_output=True,
        )
        assert bad.returncode == 1


class TestHarvestMerge:
    def _merge(self, tmp_path, recs, selftest=None):
        d = tmp_path / "results"
        d.mkdir()
        for r in recs:
            (d / f"{r['bench']}.json").write_text(json.dumps(r))
        if selftest is not None:
            (d / "selftest.json").write_text(json.dumps(
                {"metric": "selftest", "selftest": selftest}
            ))
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "harvest_merge.py"),
             str(d)],
            capture_output=True, text=True,
        )
        assert p.returncode == 0, p.stderr
        return json.loads(p.stdout), p.stderr

    def test_resnet50_heads_and_extras_ordered(self, tmp_path):
        out, _ = self._merge(
            tmp_path, [_rec("mnist"), _rec("resnet50"), _rec("gpt2")]
        )
        assert out["bench"] == "resnet50"
        assert [e["bench"] for e in out["extras"]] == ["gpt2", "mnist"]
        assert "resnet50" in out["harvested"]

    def test_minority_backend_dropped_loudly(self, tmp_path):
        out, err = self._merge(
            tmp_path,
            [_rec("resnet50"), _rec("gpt2"), _rec("mnist", backend="cpu")],
        )
        assert out["backend"] == "tpu"
        assert all(e["bench"] != "mnist" for e in out["extras"])
        assert "DROPPING mnist" in err

    def test_tpu_preferred_even_as_minority(self, tmp_path):
        out, _ = self._merge(
            tmp_path,
            [_rec("resnet50", backend="cpu"), _rec("gpt2", backend="cpu"),
             _rec("mnist", backend="tpu")],
        )
        assert out["backend"] == "tpu"
        assert out["bench"] == "mnist"

    def test_head_keeps_own_fingerprints_spread_is_window_wide(
        self, tmp_path
    ):
        recs = [
            _rec("resnet50", fingerprint_tflops_pre=500.0,
                 fingerprint_tflops_post=600.0),
            # A wedged post-probe: must reach the spread, not the head.
            _rec("moe", fingerprint_tflops_pre=450.0,
                 fingerprint_tflops_post=78.0),
        ]
        out, _ = self._merge(tmp_path, recs)
        assert out["fingerprint_tflops_pre"] == 500.0
        assert out["fingerprint_tflops_post"] == 600.0
        assert out["fingerprint_spread"] == [78.0, 600.0]

    def test_truncated_lists_missing_and_selftest_carried(self, tmp_path):
        st = {"ok": True, "summary": "9/9"}
        out, _ = self._merge(tmp_path, [_rec("resnet50")], selftest=st)
        assert out["selftest"] == st
        assert "gpt2" in out["truncated"]

    def test_nested_sweep_keys_stripped(self, tmp_path):
        out, _ = self._merge(
            tmp_path,
            [_rec("resnet50", tpu_harvest={"old": 1}, extras=[{"x": 1}],
                  harvested=["resnet50"])],
        )
        assert "tpu_harvest" not in out
        assert out["extras"] == []
        assert out["harvested"] == ["resnet50"]


class TestStampFloors:
    def _stamp(self, tmp_path, record):
        p = tmp_path / "merged.json"
        p.write_text(json.dumps(record))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "stamp_floors.py"),
             str(p)],
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        return r.stdout

    def test_per_record_fingerprints_and_unfloored_exclusion(self, tmp_path):
        head = _rec("resnet50", fingerprint_tflops_pre=500.0)
        head["rel_mfu"] = 0.08
        diag = _rec("decode_grid", fingerprint_tflops_pre=470.0)
        diag["metric"] = "decode_grid_step_time_ratio"
        other = _rec("gpt2", fingerprint_tflops_pre=480.0)
        head["extras"] = [other, diag]
        out = self._stamp(tmp_path, head)
        assert '"resnet50_metric": (1.0, 500.0),' in out
        assert '"gpt2_metric": (1.0, 480.0),' in out
        # The diagnostic must appear only as a comment, never a floor.
        assert '"decode_grid_step_time_ratio": (' not in out
        assert "deliberately unfloored" in out
        assert '"resnet50_metric": 0.08,' in out  # rel_mfu section

    def test_errored_metrics_flagged_not_stamped(self, tmp_path):
        head = _rec("resnet50", fingerprint_tflops_pre=500.0)
        head["extras"] = [{"metric": "bert_metric", "bench": "bert",
                           "error": "boom", "backend": "tpu"}]
        out = self._stamp(tmp_path, head)
        assert "ERRORED" in out
        assert "'bert'" in out or "bert" in out
        assert '"bert_metric": (' not in out


class TestStepFlops:
    """The bundled-FLOPs fallback (round 5): axon's lowering-only
    cost_analysis returns None, so bundled benches must fall back to
    analysing the compiled bundled program at flops/K — otherwise the
    record silently loses rel_mfu (how the first bundled window
    shipped without it)."""

    @pytest.fixture()
    def trainer_and_stack(self):
        import bench
        from tensorflow_examples_tpu.data.memory import train_iterator
        from tensorflow_examples_tpu.data.sources import synthetic_images
        from tensorflow_examples_tpu.train.loop import Trainer
        from tensorflow_examples_tpu.workloads import mnist

        bench.BACKEND = "cpu"
        cfg = mnist.MnistConfig(
            global_batch_size=8, log_every=10**9, checkpoint_every=0,
            eval_every=0, train_steps=10**6, watchdog_secs=0,
        )
        tr = Trainer(mnist.make_task(cfg), cfg, mesh=bench._chip_mesh())
        ds = synthetic_images(n=64, shape=(28, 28, 1), num_classes=10, seed=0)
        it = train_iterator(ds, 8, seed=0)
        yield bench, tr, bench._bundle_prep(tr, it, 1, 4)[0]
        # last_mode is flops PROVENANCE for the bench record; a test
        # that exercised the fallback must not bank "compiled-bundled/k"
        # for whatever measures flops next in this process.
        bench._step_flops.last_mode = None

    def test_bundle_uses_lowering_when_available(self, trainer_and_stack):
        bench, tr, stack = trainer_and_stack
        f = bench._step_flops(tr, stack, bundle=4)
        assert f and f > 0
        assert bench._step_flops.last_mode == "lowered"

    def test_bundle_falls_back_to_compiled_bundled(self, trainer_and_stack):
        bench, tr, stack = trainer_and_stack

        class _NoCostLowered:  # what axon's lowering analysis acts like
            def cost_analysis(self):
                return None

        tr.__dict__["_train_step"] = type(
            "Stub", (), {"lower": lambda self, *a: _NoCostLowered()}
        )()
        f = bench._step_flops(tr, stack, bundle=4)
        assert f and f > 0
        assert bench._step_flops.last_mode == "compiled-bundled/k"
        # flops are PER STEP (the bundled program's total / k): one
        # bundled analysis must not report k-fold FLOPs.
        total = tr._build_bundled_step(4).lower(
            tr.state, stack
        ).compile().cost_analysis()
        total = total[0] if isinstance(total, (list, tuple)) else total
        assert abs(f * 4 - float(total.get("flops", 0.0))) / (f * 4) < 1e-6


class TestDiagCommon:
    def test_parse_budget(self):
        from diag_common import parse_budget

        assert parse_budget(["--budget=42.5"]) == 42.5
        assert parse_budget(["--other"], default=9.0) == 9.0

    def test_make_emit_last_line_wins(self, tmp_path, capsys):
        from diag_common import make_emit

        out = {"a": 1}
        emit = make_emit(out)
        emit(True)  # watchdog snapshot
        out["b"] = 2
        emit()  # main's full record
        lines = [
            json.loads(l) for l in capsys.readouterr().out.splitlines()
        ]
        assert lines[0] == {"a": 1, "truncated": True}
        assert lines[-1] == {"a": 1, "b": 2}
        # and the consumer contract picks the full record:
        p = tmp_path / "log"
        p.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
        assert last_json_line(str(p)) == {"a": 1, "b": 2}

    def test_watchdog_emits_then_cancel_suppresses(self, capsys):
        import time as _time

        from diag_common import make_emit, start_watchdog

        t = start_watchdog(5.0, make_emit({"x": 1}))  # floor: fires at 5s...
        t.cancel()  # ...unless cancelled first
        _time.sleep(0.1)
        assert capsys.readouterr().out == ""


class TestFlashTuneSweep:
    def test_sweep_shape_interpret_cells_and_best(self):
        """Sweep mechanics on a tiny interpret-mode shape: legal cells
        only, best_* selected by min, deadline truncation honored."""
        import time as _time

        import flash_tune

        rec = flash_tune._sweep_shape(
            "tiny", 1, 1, 128, 8, True, 1, _time.monotonic() + 600
        )
        # seq 128 admits only the (128, 128) cell out of BLOCKS^2.
        assert [c["block_q"] for c in rec["cells"]] == [128]
        assert rec["best_fwd"] == rec["cells"][0]
        assert rec["best_fwdbwd"] == rec["cells"][0]
        assert "truncated" not in rec

    def test_sweep_shape_deadline_truncates(self):
        import time as _time

        import flash_tune

        rec = flash_tune._sweep_shape(
            "tiny", 1, 1, 128, 8, True, 1, _time.monotonic() - 1.0
        )
        assert rec["truncated"] is True
        assert rec["cells"] == []
        assert "best_fwd" not in rec


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))


class TestSelftestReuse:
    """bench.run_selftest must reuse a COMPLETE banked per-node harvest
    selftest (re-running the monolithic tests_tpu/ is the round-3 wedge
    pattern) and fall through when the banked record is partial."""

    def _bench(self):
        sys.path.insert(0, REPO)
        import bench

        return bench

    def _pin_budget(self, bench, monkeypatch):
        # Nearly-spent budget: any fall-through takes the "insufficient
        # budget" exit instead of spawning a real (hangable) pytest run.
        monkeypatch.setattr(
            bench, "_DEADLINE", __import__("time").monotonic() + 40
        )

    def test_banked_complete_ok_reused(self, tmp_path, monkeypatch):
        bench = self._bench()
        from kernel_source_hash import kernel_source_hash

        p = tmp_path / "merged.json"
        p.write_text(json.dumps({
            "backend": "tpu",
            "selftest": {"ok": True, "complete": True, "passed": 10,
                         "total": 10, "summary": "10/10 passed on tpu",
                         "kernel_source_hash": kernel_source_hash()},
        }))
        monkeypatch.setenv("BENCH_BANKED_HARVEST", str(p))
        self._pin_budget(bench, monkeypatch)
        out = bench.run_selftest(allow_banked=True)
        assert out["ok"] is True
        assert "banked" in out["summary"] and "10/10" in out["summary"]
        # An explicit selftest request (allow_banked default) runs fresh.
        out = bench.run_selftest()
        assert "insufficient budget" in out["summary"]

    def test_stale_kernel_hash_not_reused(self, tmp_path, monkeypatch):
        # A bank taken before an ops/ edit is stale evidence (ADVICE
        # r4): its embedded source hash diverges and reuse must refuse.
        bench = self._bench()
        p = tmp_path / "merged.json"
        p.write_text(json.dumps({
            "backend": "tpu",
            "selftest": {"ok": True, "complete": True, "passed": 10,
                         "total": 10, "summary": "10/10 passed on tpu",
                         "kernel_source_hash": "0" * 64},
        }))
        monkeypatch.setenv("BENCH_BANKED_HARVEST", str(p))
        self._pin_budget(bench, monkeypatch)
        out = bench.run_selftest(allow_banked=True)
        assert out["ok"] is False
        assert "insufficient budget" in out["summary"]  # fell through

    def test_cpu_rehearsal_bank_not_reused(self, tmp_path, monkeypatch):
        bench = self._bench()
        p = tmp_path / "merged.json"
        p.write_text(json.dumps({
            "backend": "cpu",  # rehearsal bank: NOT on-chip evidence
            "selftest": {"ok": True, "complete": True, "passed": 10,
                         "total": 10, "summary": "10/10 passed on cpu"},
        }))
        monkeypatch.setenv("BENCH_BANKED_HARVEST", str(p))
        self._pin_budget(bench, monkeypatch)
        out = bench.run_selftest(allow_banked=True)
        assert out["ok"] is False
        assert "insufficient budget" in out["summary"]

    def test_banked_partial_falls_through(self, tmp_path, monkeypatch):
        bench = self._bench()
        p = tmp_path / "merged.json"
        p.write_text(json.dumps({
            "backend": "tpu",
            "selftest": {"ok": False, "complete": False, "passed": 5,
                         "total": 10, "summary": "5/10"},
        }))
        monkeypatch.setenv("BENCH_BANKED_HARVEST", str(p))
        self._pin_budget(bench, monkeypatch)
        out = bench.run_selftest(allow_banked=True)
        assert out["ok"] is False
        assert "insufficient budget" in out["summary"]


class TestApplyFloors:
    """tools/apply_floors.py: mechanical floor restamps must be
    line-scoped (comments and unstamped metrics byte-identical) and
    refuse partial/no-op stamps unless told otherwise."""

    SRC = (
        "FLOORS = {\n"
        '    "tpu": {\n'
        "        # provenance comment stays\n"
        '        "m_a": (1.0, 10.0),  # inline note stays\n'
        '        "m_b": (2.0, 20.0),\n'
        "    },\n"
        '    "cpu": {\n'
        '        "m_a": (9.0, 0.1),\n'
        "    },\n"
        "}\n"
        "REL_MFU_FLOORS: dict = {\n"
        '    "tpu": {\n'
        '        "m_a": 0.5,\n'
        "    },\n"
        '    "cpu": {},\n'
        "}\n"
    )

    def _mod(self):
        import apply_floors

        return apply_floors

    def test_line_scoped_rewrite_preserves_comments(self):
        af = self._mod()
        out = af._rewrite(self.SRC, "FLOORS", "tpu", {"m_a": "(3.0, 30.0)"})
        assert '"m_a": (3.0, 30.0),  # inline note stays' in out
        assert '"m_b": (2.0, 20.0),' in out  # untouched
        assert '"m_a": (9.0, 0.1),' in out  # cpu block untouched
        assert "# provenance comment stays" in out

    def test_new_metric_appended_to_backend_block(self):
        af = self._mod()
        out = af._rewrite(self.SRC, "FLOORS", "tpu", {"m_new": "(7.0, 70.0)"})
        tpu_block = out.split('"cpu": {')[0]
        assert '"m_new": (7.0, 70.0),  # first floor' in tpu_block

    def test_missing_backend_refused(self):
        af = self._mod()
        with pytest.raises(SystemExit):
            af._rewrite(self.SRC, "FLOORS", "gpu", {"m_a": "(3.0, 30.0)"})

    def test_wrapped_entry_refused_not_duplicated(self):
        # A formatter-wrapped entry no longer matches the one-line
        # regex; appending would leave a duplicate dict key (ADVICE
        # r4) — the rewrite must refuse instead.
        af = self._mod()
        src = self.SRC.replace(
            '"m_b": (2.0, 20.0),',
            '"m_b": (\n            2.0, 20.0),',
        )
        with pytest.raises(SystemExit, match="m_b"):
            af._rewrite(src, "FLOORS", "tpu", {"m_b": "(5.0, 50.0)"})

    def test_bundle_protocol_stamped_with_floor(
        self, tmp_path, monkeypatch, capsys
    ):
        """A restamp carries the record's launch protocol into
        FLOOR_BUNDLES (dry-run against the real bench.py — the floors
        policy says protocol moves WITH the floor)."""
        af = self._mod()
        # bundle=4 differs from bench.py's current stamp (8) on purpose:
        # the assertion needs the rewrite to CHANGE the line, or it
        # cannot appear in the dry-run diff at all.
        rec = {
            "backend": "tpu",
            "metric": "bert_base_examples_per_sec_per_chip",
            "bench": "bert", "value": 25000.0,
            "fingerprint_tflops_pre": 50000.0, "bundle": 4,
        }
        p = tmp_path / "r.json"
        p.write_text(json.dumps(rec))
        monkeypatch.setattr(
            sys, "argv", ["apply_floors.py", str(p), "--dry-run"]
        )
        monkeypatch.chdir(REPO)
        assert af.main() == 0
        diff = capsys.readouterr().out
        assert '"bert_base_examples_per_sec_per_chip": (25000.0, 50000.0),' in diff
        assert '"bert_base_examples_per_sec_per_chip": 4,' in diff

    def test_truncated_record_needs_partial_flag(self, tmp_path, monkeypatch, capsys):
        af = self._mod()
        rec = {"backend": "tpu", "metric": "m_a", "value": 3.0,
               "fingerprint_tflops_pre": 30.0, "truncated": ["m_b"]}
        p = tmp_path / "r.json"
        p.write_text(json.dumps(rec))
        monkeypatch.setattr(sys, "argv", ["apply_floors.py", str(p)])
        monkeypatch.chdir(REPO)
        assert af.main() == 1
        assert "pass --partial" in capsys.readouterr().out


class TestKernelSourceHash:
    def test_changes_with_ops_content_and_layout(self, tmp_path):
        from kernel_source_hash import kernel_source_hash

        root = tmp_path / "repo"
        ops = root / "tensorflow_examples_tpu" / "ops"
        tt = root / "tests_tpu"
        ops.mkdir(parents=True)
        tt.mkdir()
        (ops / "k.py").write_text("a = 1\n")
        (tt / "t.py").write_text("b = 2\n")
        h0 = kernel_source_hash(str(root))
        assert h0 == kernel_source_hash(str(root))  # deterministic
        (ops / "k.py").write_text("a = 3\n")
        h1 = kernel_source_hash(str(root))
        assert h1 != h0  # content edit
        (ops / "k.py").rename(ops / "k2.py")
        assert kernel_source_hash(str(root)) != h1  # rename counts too

    def test_repo_hash_is_stable_here(self):
        from kernel_source_hash import kernel_source_hash

        assert kernel_source_hash() == kernel_source_hash()


class TestTelemetryReport:
    """tools/telemetry_report.py smoke (ISSUE 2 satellite): a run dir's
    JSONL + trace turn into the human summary and the machine record."""

    def _run_dir(self, tmp_path):
        """Handcraft a schema-valid run dir (no training needed)."""
        tdir = tmp_path / "telemetry"
        tdir.mkdir()
        base = {
            "schema_version": 1, "session_start_unix": 99.0, "gauges": {
                "telemetry/flops_per_step": 1e9,
                "telemetry/peak_flops_total": 1e12,
                "telemetry/peak_is_estimate": 0.0,
            },
        }
        lines = [
            dict(base, kind="window", step=10, time_unix=100.0,
                 metrics={"train/loss": 2.0},
                 counters={"train/steps_total": 10,
                           "data/batches_fetched": 10},
                 derived={"examples_per_sec": 640.0,
                          "tokens_per_sec": None,
                          "step_time_p50": 0.010, "step_time_p95": 0.020,
                          "mfu": 0.01, "goodput": 1.0}),
            dict(base, kind="window", step=20, time_unix=101.0,
                 metrics={"train/loss": 1.0},
                 counters={"train/steps_total": 20,
                           "data/batches_fetched": 20,
                           "resilience/bad_steps": 2},
                 derived={"examples_per_sec": 660.0,
                          "tokens_per_sec": None,
                          "step_time_p50": 0.011, "step_time_p95": 0.021,
                          "mfu": 0.011, "goodput": 0.9}),
            dict(base, kind="final", step=20, time_unix=101.5, metrics={},
                 counters={"train/steps_total": 20,
                           "data/batches_fetched": 20,
                           "resilience/bad_steps": 2,
                           "checkpoint/saves": 1},
                 derived={"examples_per_sec": None, "tokens_per_sec": None,
                          "step_time_p50": 0.011, "step_time_p95": 0.021,
                          "mfu": None, "goodput": 0.9},
                 exit_reason="complete"),
        ]
        with open(tdir / "metrics.jsonl", "w") as f:
            f.write("\n".join(json.dumps(l) for l in lines) + "\n")
            f.write("{torn tail never valid json\n")  # must be skipped
        with open(tdir / "trace.json", "w") as f:
            json.dump({"traceEvents": [
                {"name": "device_step", "ph": "X", "ts": 0.0, "dur": 9000.0,
                 "pid": 0, "tid": 0},
                {"name": "data_fetch", "ph": "X", "ts": 0.0, "dur": 1000.0,
                 "pid": 0, "tid": 0},
            ]}, f)
        return tmp_path

    def test_summary_and_json_record(self, tmp_path, capsys):
        import telemetry_report

        wd = self._run_dir(tmp_path)
        out_json = tmp_path / "report.json"
        rc = telemetry_report.main([str(wd), "--json", str(out_json)])
        stdout = capsys.readouterr().out
        assert rc == 0, stdout
        # The acceptance quartet, human-readable:
        assert "examples/sec" in stdout
        assert "p50" in stdout and "p95" in stdout
        assert "mfu estimate" in stdout
        assert "goodput: 90.00%" in stdout
        assert "ended: complete" in stdout
        assert "skipped 1 line" in stdout  # torn tail counted loudly
        assert "device_step" in stdout  # trace phase breakdown
        rec = json.load(open(out_json))
        assert rec["examples_per_sec_last"] == 660.0
        assert rec["examples_per_sec_mean"] == 650.0
        assert rec["step_time_p50"] == 0.011
        assert rec["mfu"] == 0.011
        assert rec["mfu_peak_is_estimate"] is False
        assert rec["goodput"] == 0.9
        assert rec["exit_reason"] == "complete"
        assert rec["trace_phases"]["device_step"]["total_ms"] == 9.0

    def test_missing_run_dir_exits_1(self, tmp_path, capsys):
        import telemetry_report

        assert telemetry_report.main([str(tmp_path / "nope")]) == 1
        assert "no telemetry found" in capsys.readouterr().err

    def test_preempt_resume_sessions_aggregated(self, tmp_path, capsys):
        """Counters are cumulative PER PROCESS: a preempted-then-resumed
        run's report must sum the sessions, not read only the last
        line (which would hide session 1's preemption entirely)."""
        import telemetry_report

        tdir = tmp_path / "telemetry"
        tdir.mkdir()
        base = {"schema_version": 1, "gauges": {}, "metrics": {},
                "derived": {"examples_per_sec": None,
                            "tokens_per_sec": None, "step_time_p50": 0.01,
                            "step_time_p95": 0.02, "mfu": None,
                            "goodput": None}}
        lines = [
            # session 1: preempted at step 50, 2 bad steps
            dict(base, kind="final", step=50, time_unix=100.0,
                 session_start_unix=90.0,
                 counters={"train/steps_total": 50,
                           "resilience/bad_steps": 2,
                           "resilience/preemptions": 1},
                 exit_reason="preempt"),
            # session 2: fresh process, counters restart, completes
            dict(base, kind="final", step=100, time_unix=200.0,
                 session_start_unix=190.0,
                 counters={"train/steps_total": 50,
                           "checkpoint/restores": 1},
                 exit_reason="complete"),
        ]
        with open(tdir / "metrics.jsonl", "w") as f:
            f.write("\n".join(json.dumps(l) for l in lines) + "\n")
        assert telemetry_report.main([str(tmp_path), "--json", "-"]) == 0
        out = capsys.readouterr().out
        rec = json.loads(out[out.index("{"):])  # summary carries no braces
        assert rec["sessions"] == 2
        assert rec["counters"]["train/steps_total"] == 100
        assert rec["counters"]["resilience/preemptions"] == 1
        assert rec["counters"]["resilience/bad_steps"] == 2
        assert rec["goodput"] == pytest.approx(0.98)  # 98/100 across both
        assert "in 2 session(s)" in out
        assert "preemptions=1" in out


class TestTelemetryReportShards:
    """ISSUE 4 satellite: a run dir holding per-host telemetry shards
    reports per-host figures and flags the slowest host; single-shard
    dirs keep the exact pre-fleet behavior (pinned above)."""

    def _line(self, host, step, *, p50, p95, kind="window", **over):
        line = {
            "schema_version": 3, "kind": kind, "host": host, "step": step,
            "time_unix": 100.0 + step, "session_start_unix": 99.0,
            "metrics": {"train/loss": 2.0}, "gauges": {},
            "counters": {"train/steps_total": step},
            "derived": {"examples_per_sec": 640.0, "tokens_per_sec": None,
                        "step_time_p50": p50, "step_time_p95": p95,
                        "mfu": 0.01, "goodput": 1.0},
        }
        line.update(over)
        return line

    def _fleet_dir(self, tmp_path):
        """The REAL multi-host layout: process 0's stream is
        metrics.jsonl (no host-0 shard — sinks.make_sinks writes none),
        hosts k>0 each have telemetry.host{k}.jsonl."""
        tdir = tmp_path / "telemetry"
        tdir.mkdir()
        fleet = {
            "hosts": [
                {"host": 0, "step_time_p50": 0.01, "step_time_p95": 0.011,
                 "data_fetch_p95": 0.001, "steps_lost": 0,
                 "peak_live_bytes": 1024, "io_retries": 0,
                 "batches_skipped": 0},
                {"host": 1, "step_time_p50": 0.04, "step_time_p95": 0.05,
                 "data_fetch_p95": 0.045, "steps_lost": 0,
                 "peak_live_bytes": 1024, "io_retries": 7,
                 "batches_skipped": 0},
            ],
            "slowest_host": 1, "skew": 4.5, "side": "input",
            "straggler": True,
        }
        main_lines = [
            self._line(0, 10, p50=0.01, p95=0.011),
            self._line(0, 10, p50=0.01, p95=0.011, kind="fleet",
                       fleet=fleet),
            self._line(0, 20, p50=0.01, p95=0.011, kind="final",
                       metrics={}, exit_reason="complete"),
        ]
        shard1 = [
            self._line(1, 10, p50=0.04, p95=0.05),
            self._line(1, 20, p50=0.04, p95=0.05, kind="final",
                       metrics={}, exit_reason="complete",
                       counters={"train/steps_total": 20,
                                 "resilience/steps_lost": 2}),
        ]
        with open(tdir / "metrics.jsonl", "w") as f:
            f.write("\n".join(json.dumps(l) for l in main_lines) + "\n")
        with open(tdir / "telemetry.host1.jsonl", "w") as f:
            f.write("\n".join(json.dumps(l) for l in shard1) + "\n")
        return tmp_path

    def test_shards_merged_and_slowest_flagged(self, tmp_path, capsys):
        import telemetry_report

        wd = self._fleet_dir(tmp_path)
        rc = telemetry_report.main([str(wd), "--json", "-"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "fleet: 2 host shard(s); SLOWEST host 1" in out
        assert "host 0:" in out and "host 1:" in out
        assert "<- SLOWEST" in out
        assert "fleet skew (last fleet line): 4.50x" in out
        assert "slowest host 1, input-side" in out
        assert "STRAGGLER flagged in 1 window(s)" in out
        rec = json.loads(out[out.index("{"):])
        assert [h["host"] for h in rec["hosts"]] == [0, 1]
        assert rec["slowest_host"] == 1
        assert rec["hosts"][1]["step_time_p95"] == 0.05
        assert rec["hosts"][1]["steps_lost"] == 2
        assert rec["fleet"]["side"] == "input"
        assert rec["fleet_straggler_windows"] == 1

    def test_single_shard_dir_unchanged(self, tmp_path, capsys):
        """No host shards -> no fleet table, hosts is null (the summary
        and record shape of a pre-ISSUE-4 run dir)."""
        import telemetry_report

        tdir = tmp_path / "telemetry"
        tdir.mkdir()
        with open(tdir / "metrics.jsonl", "w") as f:
            f.write(json.dumps(self._line(0, 10, p50=0.01, p95=0.02)) + "\n")
        rc = telemetry_report.main([str(tmp_path), "--json", "-"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "host shard" not in out
        rec = json.loads(out[out.index("{"):])
        assert rec["hosts"] is None
        assert rec["slowest_host"] is None

    def test_shards_only_dir_still_reports(self, tmp_path, capsys):
        """A dir with ONLY host shards (host 0's record lost) reports
        from the lowest shard instead of erroring."""
        import telemetry_report

        tdir = tmp_path / "telemetry"
        tdir.mkdir()
        with open(tdir / "telemetry.host1.jsonl", "w") as f:
            f.write(
                json.dumps(self._line(1, 10, p50=0.01, p95=0.02)) + "\n"
            )
        rc = telemetry_report.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fleet: 1 host shard(s)" in out


class TestRunDiff:
    """tools/run_diff.py (ISSUE 4 tentpole (3)): regression attribution
    between two run dirs, ranked, machine-consumable by bench_gate."""

    def _dir(self, root, name, *, p50=0.010, p95=0.020, mfu=0.010,
             eps=640.0, device_ms=9000.0, fetch_ms=1000.0):
        tdir = root / name / "telemetry"
        tdir.mkdir(parents=True)
        base = {
            "schema_version": 1, "session_start_unix": 99.0, "gauges": {},
        }
        lines = [
            dict(base, kind="window", step=10, time_unix=100.0,
                 metrics={"train/loss": 2.0},
                 counters={"train/steps_total": 10},
                 derived={"examples_per_sec": eps, "tokens_per_sec": None,
                          "step_time_p50": p50, "step_time_p95": p95,
                          "mfu": mfu, "goodput": 1.0}),
            dict(base, kind="final", step=10, time_unix=101.0, metrics={},
                 counters={"train/steps_total": 10},
                 derived={"examples_per_sec": None, "tokens_per_sec": None,
                          "step_time_p50": p50, "step_time_p95": p95,
                          "mfu": None, "goodput": 1.0},
                 exit_reason="complete"),
        ]
        with open(tdir / "metrics.jsonl", "w") as f:
            f.write("\n".join(json.dumps(l) for l in lines) + "\n")
        with open(tdir / "trace.json", "w") as f:
            json.dump({"traceEvents": [
                {"name": "device_step", "ph": "X", "ts": 0.0,
                 "dur": device_ms * 1e3, "pid": 0, "tid": 0},
                {"name": "data_fetch", "ph": "X", "ts": 0.0,
                 "dur": fetch_ms * 1e3, "pid": 0, "tid": 0},
            ]}, f)
        return str(root / name)

    def test_injected_regression_ranked_first(self, tmp_path, capsys):
        """ISSUE 4 acceptance: the injected step-time regression is the
        top-ranked finding."""
        import run_diff

        a = self._dir(tmp_path, "a")
        b = self._dir(tmp_path, "b", p50=0.013, p95=0.027)  # +30/+35%
        out_json = tmp_path / "diff.json"
        rc = run_diff.main([a, b, "--json", str(out_json)])
        out = capsys.readouterr().out
        assert rc == 0, out
        doc = json.load(open(out_json))
        assert doc["regressions"] == 2
        assert doc["ranked"][0]["metric"] == "step_time_p95"  # largest
        assert doc["ranked"][1]["metric"] == "step_time_p50"
        assert doc["ranked"][0]["verdict"] == "regressed"
        first = out.index("REGRESSED step_time_p95")
        assert first < out.index("REGRESSED step_time_p50")
        # unchanged metrics rank after, improvements would sit between
        assert out.index("unchanged goodput") > first

    def test_improvement_and_span_attribution(self, tmp_path, capsys):
        import run_diff

        a = self._dir(tmp_path, "a")
        b = self._dir(tmp_path, "b", mfu=0.02, device_ms=13500.0)
        rc = run_diff.main([a, b, "--json", "-"])
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out[out.index('{\n'):])
        by_metric = {d["metric"]: d for d in doc["ranked"]}
        assert by_metric["mfu"]["verdict"] == "improved"
        span = by_metric["span/device_step_total_ms"]
        assert span["verdict"] == "regressed"
        assert span["rel_change"] == pytest.approx(0.5)
        assert doc["ranked"][0]["metric"] == "span/device_step_total_ms"

    def test_self_compare_is_clean(self, tmp_path, capsys):
        import run_diff

        a = self._dir(tmp_path, "a")
        rc = run_diff.main([a, a, "--fail-on-regression"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "0 regressed" in out
        assert "REGRESSED" not in out

    def test_fail_on_regression_exit_code(self, tmp_path, capsys):
        import run_diff

        a = self._dir(tmp_path, "a")
        b = self._dir(tmp_path, "b", p50=0.02)
        assert run_diff.main([a, b]) == 0  # report-only by default
        assert run_diff.main([a, b, "--fail-on-regression"]) == 1

    def test_missing_run_exits_2(self, tmp_path, capsys):
        import run_diff

        a = self._dir(tmp_path, "a")
        assert run_diff.main([a, str(tmp_path / "nope")]) == 2
        assert "run_b" in capsys.readouterr().err

    def test_zero_baseline_stays_valid_json(self, tmp_path, capsys):
        """recompiles 0 -> 2 has no finite ratio; the doc must still be
        strict-parseable JSON (no bare Infinity) and rank the jump
        first."""
        import run_diff

        base = {"windows": 1, "counters": {}, "first_step": 0,
                "last_step": 10, "exit_reason": "complete",
                "recompiles": 0, "step_time_p50": 0.01}
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps(base))
        b.write_text(json.dumps(dict(base, recompiles=2)))
        out_json = tmp_path / "diff.json"
        assert run_diff.main(
            [str(a), str(b), "--json", str(out_json)]
        ) == 0
        out = capsys.readouterr().out
        assert "REGRESSED recompiles" in out and "0->new" in out
        raw = out_json.read_text()
        assert "Infinity" not in raw
        doc = json.loads(raw)  # strict parse succeeds
        assert doc["ranked"][0]["metric"] == "recompiles"
        assert doc["ranked"][0]["rel_change"] is None
        assert doc["regressions"] == 1

    def test_absent_fields_not_compared(self, tmp_path, capsys):
        """v1 records (no memory watermark) list the field as not
        comparable instead of inventing a delta."""
        import run_diff

        a = self._dir(tmp_path, "a")
        rec = {"windows": 1, "counters": {}, "step_time_p50": 0.01,
               "step_time_p95": 0.02, "examples_per_sec_mean": 640.0,
               "mfu": 0.01, "goodput": 1.0, "peak_live_bytes": 4096,
               "first_step": 0, "last_step": 10, "exit_reason": "complete"}
        b = tmp_path / "b_report.json"
        b.write_text(json.dumps(rec))
        rc = run_diff.main([a, str(b)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "not comparable: peak_live_bytes: absent in A" in out

    def test_json_feeds_bench_gate_record_mode(self, tmp_path, capsys):
        """The --json doc is directly gateable: stamp floors from run
        A's report, then bench_gate --record the A-vs-B diff doc — the
        regressed candidate fails the gate."""
        import bench_gate
        import run_diff
        import telemetry_report

        a = self._dir(tmp_path, "a")
        b = self._dir(tmp_path, "b", p50=0.013, p95=0.027)
        report_a = tmp_path / "report_a.json"
        assert telemetry_report.main([a, "--json", str(report_a)]) == 0
        floors = tmp_path / "floors.json"
        assert bench_gate.main(
            ["--stamp", str(report_a), "--floors", str(floors)]
        ) == 0
        diff_json = tmp_path / "diff.json"
        assert run_diff.main([a, b, "--json", str(diff_json)]) == 0
        assert bench_gate.main(
            ["--record", str(diff_json), "--floors", str(floors)]
        ) == 1
        out = capsys.readouterr().out
        assert "[FAIL] step_time_p50" in out
        # and the self-compare diff doc passes the same gate
        self_json = tmp_path / "self.json"
        assert run_diff.main([a, a, "--json", str(self_json)]) == 0
        assert bench_gate.main(
            ["--record", str(self_json), "--floors", str(floors)]
        ) == 0

    def test_serving_records_rank_serving_regressions_first(
        self, tmp_path, capsys
    ):
        """ISSUE 8 satellite: run_diff consumes serving bench records
        (the router's canary per-set docs) and ranks TTFT/TPOT/
        prefix-hit regressions first — the canary-compare path."""
        import run_diff

        base = {
            "bench": "serve_router_set", "ttft_p95_ms": 50.0,
            "tpot_p95_ms": 10.0, "req_per_s": 40.0,
            "tok_per_s": 300.0, "prefix_hit_rate": 0.25,
        }
        canary = dict(base, ttft_p95_ms=100.0, prefix_hit_rate=0.05,
                      tok_per_s=310.0)
        a, b = tmp_path / "base.json", tmp_path / "canary.json"
        a.write_text(json.dumps(base))
        b.write_text(json.dumps(canary))
        rc = run_diff.main(
            [str(a), str(b), "--fail-on-regression"]
        )
        out = capsys.readouterr().out
        assert rc == 1  # the canary regressed; compare says so
        lines = [l for l in out.splitlines() if "REGRESSED" in l]
        # Both serving regressions found, largest relative change
        # first (2x TTFT = +100% outranks the -80% hit-rate loss),
        # improvements after.
        assert len(lines) == 2
        assert "ttft_p95_ms" in lines[0]
        assert "prefix_hit_rate" in lines[1]
        assert "improved " in out and "tok_per_s" in out


def test_ci_perf_gates_run_in_tier1(tmp_path):
    """ISSUE 4 CI satellite, at the subprocess level the CI would use:
    bench_gate trajectory mode over the banked BENCH_r0*.json rounds
    AND a run_diff --json self-compare both exit 0 — a perf-record or
    report schema break fails the tier-1 pass instead of silently
    rotting. (Fast: both are pure-JSON CPU paths.)"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    files = sorted(
        os.path.join(REPO, f)
        for f in os.listdir(REPO)
        if re.fullmatch(r"BENCH_r\d+\.json", f)
    )
    assert files, "no banked BENCH_*.json trajectory in the repo"
    gate = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         *files],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert gate.returncode == 0, gate.stdout + gate.stderr
    assert "0 regressed" in gate.stdout

    # run_diff self-compare: a run dir diffed against itself is clean.
    run = TestRunDiff()._dir(tmp_path, "self")
    diff = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_diff.py"),
         run, run, "--json", "-", "--fail-on-regression"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert diff.returncode == 0, diff.stdout + diff.stderr
    assert "0 regressed" in diff.stdout
    doc = json.loads(diff.stdout[diff.stdout.index('{\n'):])
    assert doc["regressions"] == 0


class TestBenchGate:
    """tools/bench_gate.py (ISSUE 3 tentpole (4)): the CI perf gate must
    pass on the committed BENCH_r0*.json trajectory and fail on a
    synthetic regression — in both its trajectory and telemetry-record
    modes."""

    def _gate(self, argv):
        import bench_gate

        return bench_gate.main(argv)

    def test_banked_trajectory_passes(self, capsys):
        files = sorted(
            os.path.join(REPO, f)
            for f in os.listdir(REPO)
            if re.fullmatch(r"BENCH_r\d+\.json", f)
        )
        assert files, "no banked BENCH_*.json trajectory in the repo"
        rc = self._gate(files)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "0 regressed" in out
        assert "[PASS]" in out  # the gate actually compared something
        # Off-rig rounds are skipped under the floors policy, loudly.
        assert "comparability window" in out

    def test_synthetic_step_time_regression_fails(self, tmp_path, capsys):
        """ISSUE 3 acceptance: a 20% step-time regression (on a
        comparable rig fingerprint) exits non-zero."""
        import bench

        floor, fp = bench.FLOORS["tpu"]["mnist_mlp_step_time"]
        rec = {
            "backend": "tpu",
            "metric": "mnist_mlp_step_time",
            "value": floor * 1.2,
            "fingerprint_tflops_pre": fp,
        }
        p = tmp_path / "regressed.json"
        p.write_text(json.dumps(rec))
        rc = self._gate([str(p)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "[FAIL] mnist_mlp_step_time" in out

    def test_off_rig_regression_skipped_not_failed(self, tmp_path, capsys):
        import bench

        floor, fp = bench.FLOORS["tpu"]["gpt2_124m_tokens_per_sec"]
        rec = {
            "backend": "tpu",
            "metric": "gpt2_124m_tokens_per_sec",
            "value": floor * 0.5,  # would regress...
            "fingerprint_tflops_pre": fp * 10,  # ...but on another rig
        }
        p = tmp_path / "offrig.json"
        p.write_text(json.dumps(rec))
        assert self._gate([str(p)]) == 0
        assert "comparability window" in capsys.readouterr().out

    def test_empty_gate_is_an_error(self, tmp_path, capsys):
        p = tmp_path / "nothing.json"
        p.write_text(json.dumps({"rc": 1, "tail": "no records here"}))
        assert self._gate([str(p)]) == 2

    def _record(self, tmp_path, **over):
        rec = {
            "step_time_p50": 0.010,
            "step_time_p95": 0.020,
            "mfu": 0.010,
            "goodput": 1.0,
            "peak_live_bytes": 1_000_000,
            "examples_per_sec_mean": 640.0,
        }
        rec.update(over)
        p = tmp_path / "report.json"
        p.write_text(json.dumps(rec))
        return p

    def test_stamp_then_gate_record(self, tmp_path, capsys):
        good = self._record(tmp_path)
        floors = tmp_path / "floors.json"
        assert self._gate(
            ["--stamp", str(good), "--floors", str(floors)]
        ) == 0
        assert self._gate(
            ["--record", str(good), "--floors", str(floors)]
        ) == 0
        # 20% step-time regression beyond the 10% threshold: fail.
        bad = self._record(tmp_path, step_time_p50=0.012)
        assert self._gate(
            ["--record", str(bad), "--floors", str(floors)]
        ) == 1
        out = capsys.readouterr().out
        assert "[FAIL] step_time_p50" in out
        # memory blow-up beyond threshold: fail too.
        bad = self._record(tmp_path, peak_live_bytes=2_000_000)
        assert self._gate(
            ["--record", str(bad), "--floors", str(floors)]
        ) == 1

    def test_v1_record_missing_fields_skip_gracefully(
        self, tmp_path, capsys
    ):
        """A schema-v1 run's record (no peak_live_bytes) skips the
        memory floor instead of failing it."""
        good = self._record(tmp_path)
        floors = tmp_path / "floors.json"
        self._gate(["--stamp", str(good), "--floors", str(floors)])
        v1 = self._record(tmp_path, peak_live_bytes=None)
        assert self._gate(
            ["--record", str(v1), "--floors", str(floors)]
        ) == 0
        out = capsys.readouterr().out
        assert "[SKIP] peak_live_bytes: absent from record" in out

    def _serve_record(self, tmp_path, name="serve.json", **over):
        rec = {
            "bench": "serve_router",
            "ttft_p50_ms": 30.0,
            "ttft_p95_ms": 60.0,
            "tpot_p50_ms": 8.0,
            "tpot_p95_ms": 14.0,
            "e2e_p95_ms": 150.0,
            "req_per_s": 40.0,
            "tok_per_s": 320.0,
            "prefix_hit_rate": 0.2,
            "post_warmup_recompiles": 0,
        }
        rec.update(over)
        p = tmp_path / name
        p.write_text(json.dumps(rec))
        return p

    def test_serve_router_record_stamps_and_gates(self, tmp_path, capsys):
        """ISSUE 8 satellite: bench_gate accepts the serve_router
        record keys — latency maxima, throughput/prefix-hit minima,
        recompiles pinned — in both --stamp and --record modes."""
        good = self._serve_record(tmp_path)
        floors = tmp_path / "serve_floors.json"
        assert self._gate(
            ["--stamp", str(good), "--floors", str(floors)]
        ) == 0
        with open(floors) as f:
            stamped = json.load(f)
        assert stamped["ttft_p95_ms"] == {"max": 60.0}
        assert stamped["tok_per_s"] == {"min": 320.0}
        assert stamped["prefix_hit_rate"] == {"min": 0.2}
        assert self._gate(
            ["--record", str(good), "--floors", str(floors)]
        ) == 0
        # A 2x TTFT regression fails; so does a prefix-cache collapse.
        bad = self._serve_record(
            tmp_path, "bad.json", ttft_p95_ms=120.0
        )
        assert self._gate(
            ["--record", str(bad), "--floors", str(floors)]
        ) == 1
        assert "[FAIL] ttft_p95_ms" in capsys.readouterr().out
        bad = self._serve_record(
            tmp_path, "bad2.json", prefix_hit_rate=0.0
        )
        assert self._gate(
            ["--record", str(bad), "--floors", str(floors)]
        ) == 1

    def test_chaos_error_rate_gated_at_zero(self, tmp_path, capsys):
        """ISSUE 10 satellite: the serve_chaos availability record
        gates ``error_rate`` with a max of 0 — the threshold slack
        multiplies the zero bound into zero, so ONE failed request
        under the replica kill regresses the gate. ``p95_vs_baseline``
        gates as a declared-multiple maximum."""
        rec = {
            "bench": "serve_chaos",
            "error_rate": 0.0,
            "p95_vs_baseline": 3.0,
            "failover_count": 2,
        }
        good = tmp_path / "chaos.json"
        good.write_text(json.dumps(rec))
        floors = tmp_path / "chaos_floors.json"
        assert self._gate(
            ["--stamp", str(good), "--floors", str(floors)]
        ) == 0
        with open(floors) as f:
            stamped = json.load(f)
        assert stamped["error_rate"] == {"max": 0.0}
        assert stamped["p95_vs_baseline"] == {"max": 3.0}
        assert self._gate(
            ["--record", str(good), "--floors", str(floors)]
        ) == 0
        bad = tmp_path / "chaos_bad.json"
        bad.write_text(json.dumps(dict(rec, error_rate=0.05)))
        assert self._gate(
            ["--record", str(bad), "--floors", str(floors)]
        ) == 1
        assert "[FAIL] error_rate" in capsys.readouterr().out
        worse = tmp_path / "chaos_worse.json"
        worse.write_text(json.dumps(dict(rec, p95_vs_baseline=9.0)))
        assert self._gate(
            ["--record", str(worse), "--floors", str(floors)]
        ) == 1

    def test_spec_speedup_stamps_and_gates(self, tmp_path, capsys):
        """ISSUE 11 satellite: the serve_spec record's tpot_speedup
        gates as a stamped MINIMUM — a drafter/verify regression that
        quietly eats the speedup fails like any other perf loss."""
        rec = {
            "bench": "serve_spec",
            "tpot_speedup": 2.1,
            "draft_hit_rate": 0.9,
            "accepted_per_step": 4.2,
        }
        good = tmp_path / "spec.json"
        good.write_text(json.dumps(rec))
        floors = tmp_path / "spec_floors.json"
        assert self._gate(
            ["--stamp", str(good), "--floors", str(floors)]
        ) == 0
        with open(floors) as f:
            stamped = json.load(f)
        assert stamped["tpot_speedup"] == {"min": 2.1}
        assert stamped["draft_hit_rate"] == {"min": 0.9}
        assert self._gate(
            ["--record", str(good), "--floors", str(floors)]
        ) == 0
        bad = tmp_path / "spec_bad.json"
        bad.write_text(json.dumps(dict(rec, tpot_speedup=1.0)))
        assert self._gate(
            ["--record", str(bad), "--floors", str(floors)]
        ) == 1
        assert "[FAIL] tpot_speedup" in capsys.readouterr().out

    def test_affinity_hit_rate_stamps_and_gates(self, tmp_path, capsys):
        """ISSUE 12 satellite: the serve_affinity record's
        with-affinity hit rate gates as a stamped MINIMUM — a scheduler
        regression that quietly reverts the fleet to cache-blind
        dispatch fails CI like any other perf loss."""
        rec = {
            "bench": "serve_affinity",
            "prefix_hit_rate_affinity": 0.5,
            "prefix_hit_rate_no_affinity": 0.33,
            "affinity_hit_gain": 0.17,
        }
        good = tmp_path / "affinity.json"
        good.write_text(json.dumps(rec))
        floors = tmp_path / "affinity_floors.json"
        assert self._gate(
            ["--stamp", str(good), "--floors", str(floors)]
        ) == 0
        with open(floors) as f:
            stamped = json.load(f)
        assert stamped["prefix_hit_rate_affinity"] == {"min": 0.5}
        assert self._gate(
            ["--record", str(good), "--floors", str(floors)]
        ) == 0
        bad = tmp_path / "affinity_bad.json"
        bad.write_text(
            json.dumps(dict(rec, prefix_hit_rate_affinity=0.1))
        )
        assert self._gate(
            ["--record", str(bad), "--floors", str(floors)]
        ) == 1
        assert "[FAIL] prefix_hit_rate_affinity" in capsys.readouterr().out

    def test_affinity_keys_ranked_by_run_diff(self, tmp_path):
        """ISSUE 12 satellite: the affinity keys land in run_diff's
        DIFF_KEYS/GATE_KEYS — an affinity regression ranks and the
        candidate's rate flattens for bench_gate --record."""
        import run_diff

        a = {"bench": "serve_affinity", "prefix_hit_rate_affinity": 0.5,
             "affinity_hit_gain": 0.2}
        b = {"bench": "serve_affinity", "prefix_hit_rate_affinity": 0.2,
             "affinity_hit_gain": 0.0}
        a_path, b_path = tmp_path / "a.json", tmp_path / "b.json"
        a_path.write_text(json.dumps(a))
        b_path.write_text(json.dumps(b))
        out = tmp_path / "diff.json"
        rc = run_diff.main(
            [str(a_path), str(b_path), "--json", str(out)]
        )
        assert rc == 0
        with open(out) as f:
            doc = json.load(f)
        ranked = {d["metric"]: d["verdict"] for d in doc["ranked"]}
        assert ranked["prefix_hit_rate_affinity"] == "regressed"
        assert doc["prefix_hit_rate_affinity"] == 0.2

    def test_quant_keys_stamp_and_gate(self, tmp_path, capsys):
        """ISSUE 15 satellite: the serve_quant record's
        tpot_speedup_quant gates as a stamped MINIMUM and
        hbm_bytes_per_replica as a MAXIMUM — a dequant-path regression
        that eats the speedup, or a registry change that quietly grows
        the per-replica footprint, fails CI like any other perf loss."""
        rec = {
            "bench": "serve_quant",
            "tpot_speedup_quant": 1.03,
            "hbm_bytes_per_replica": 41132,
        }
        good = tmp_path / "quant.json"
        good.write_text(json.dumps(rec))
        floors = tmp_path / "quant_floors.json"
        assert self._gate(
            ["--stamp", str(good), "--floors", str(floors)]
        ) == 0
        with open(floors) as f:
            stamped = json.load(f)
        assert stamped["tpot_speedup_quant"] == {"min": 1.03}
        assert stamped["hbm_bytes_per_replica"] == {"max": 41132}
        assert self._gate(
            ["--record", str(good), "--floors", str(floors)]
        ) == 0
        slow = tmp_path / "quant_slow.json"
        slow.write_text(json.dumps(dict(rec, tpot_speedup_quant=0.4)))
        assert self._gate(
            ["--record", str(slow), "--floors", str(floors)]
        ) == 1
        assert "[FAIL] tpot_speedup_quant" in capsys.readouterr().out
        fat = tmp_path / "quant_fat.json"
        fat.write_text(
            json.dumps(dict(rec, hbm_bytes_per_replica=9 * 41132))
        )
        assert self._gate(
            ["--record", str(fat), "--floors", str(floors)]
        ) == 1
        assert "[FAIL] hbm_bytes_per_replica" in capsys.readouterr().out

    def test_quant_keys_ranked_by_run_diff(self, tmp_path):
        """ISSUE 15 satellite: the quant keys land in run_diff's
        DIFF_KEYS/GATE_KEYS — a quant regression ranks and the
        candidate's values flatten for bench_gate --record."""
        import run_diff

        a = {"bench": "serve_quant", "tpot_speedup_quant": 1.1,
             "hbm_bytes_per_replica": 41132, "stream_agreement": 1.0}
        b = {"bench": "serve_quant", "tpot_speedup_quant": 0.6,
             "hbm_bytes_per_replica": 41132, "stream_agreement": 0.8}
        a_path, b_path = tmp_path / "qa.json", tmp_path / "qb.json"
        a_path.write_text(json.dumps(a))
        b_path.write_text(json.dumps(b))
        out = tmp_path / "qdiff.json"
        rc = run_diff.main(
            [str(a_path), str(b_path), "--json", str(out)]
        )
        assert rc == 0
        with open(out) as f:
            doc = json.load(f)
        ranked = {d["metric"]: d["verdict"] for d in doc["ranked"]}
        assert ranked["tpot_speedup_quant"] == "regressed"
        assert ranked["stream_agreement"] == "regressed"
        assert doc["tpot_speedup_quant"] == 0.6
        assert doc["hbm_bytes_per_replica"] == 41132

    def test_floorless_report_lists_unbanked_gate_keys(
        self, tmp_path, capsys
    ):
        """ISSUE 11 satellite: the floorless-keys report WARNS (exit 0)
        for every gate key with no banked floor — the ROADMAP standing
        note's harvest list (sharded_step_time, serving TTFT/TPOT/
        prefix-hit, chaos p95) made explicit — and drops keys a
        stamped floors file covers."""
        rc = self._gate(["--floorless-report"])
        out = capsys.readouterr().out
        assert rc == 0
        for key in ("sharded_step_time", "ttft_p95_ms", "tpot_p95_ms",
                    "prefix_hit_rate", "p95_vs_baseline",
                    "tpot_speedup",
                    # ISSUE 13: the overload/traffic keys stay on the
                    # harvest list until a TPU floor is stamped.
                    "ttft_p95_interactive_ms", "ttft_p95_batch_ms",
                    "shed_rate_interactive", "scale_up_latency_s",
                    # ISSUE 15: the quantization pair joins it (the
                    # CPU CI ratio is dispatch-bound ~1.0; the
                    # memory-bound floor needs the HBM rig).
                    "tpot_speedup_quant", "hbm_bytes_per_replica"):
            assert f"[WARN] gate key '{key}'" in out, key
        # A stamped floor removes its key from the report.
        floors = tmp_path / "floors.json"
        floors.write_text(json.dumps({"tpot_speedup": {"min": 2.0}}))
        rc = self._gate(["--floorless-report", "--floors", str(floors)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "'tpot_speedup'" not in out
        assert "'sharded_step_time'" in out

    def test_trajectory_gate_appends_floorless_warnings(self, capsys):
        files = sorted(
            os.path.join(REPO, f)
            for f in os.listdir(REPO)
            if re.fullmatch(r"BENCH_r\d+\.json", f)
        )
        assert self._gate(files) == 0
        out = capsys.readouterr().out
        assert "bench_gate floorless:" in out
        assert "[WARN] gate key 'sharded_step_time'" in out


class TestFaultInjectServe:
    """ISSUE 10 satellite: tools/fault_inject.py --serve arms the
    serving fault grammar in the child's environment."""

    def test_serve_spec_exported_to_child(self, capsys):
        import fault_inject

        rc = fault_inject.main([
            "--serve", "--spec", "crash@1:4,badhealth@0:2", "--",
            sys.executable, "-c",
            "import os, sys; "
            "sys.exit(0 if os.environ.get('TPU_SERVE_FAULT_INJECT')"
            " == 'crash@1:4,badhealth@0:2' else 3)",
        ])
        assert rc == 0

    def test_serve_spec_validated_before_spawn(self, capsys):
        import fault_inject

        with pytest.raises(ValueError, match="unknown serve fault"):
            fault_inject.main([
                "--serve", "--spec", "sigterm@5", "--",
                sys.executable, "-c", "raise SystemExit(9)",
            ])
        # ...and the train grammar rejects serve kinds symmetrically.
        with pytest.raises(ValueError, match="unknown fault kind"):
            fault_inject.main([
                "--spec", "crash@1:4", "--",
                sys.executable, "-c", "raise SystemExit(9)",
            ])


class TestHostInputBench:
    """ISSUE 6 CI satellite: the input-pipeline smoke — a BENCH-style
    record from the real reader+worker pipeline, bit-identity verified,
    on BOTH decode stages (native C++ and the tf/numpy fallback)."""

    def _run(self, capsys, monkeypatch, tmp_path, native: bool):
        import host_input_bench

        monkeypatch.setenv(
            "TFE_TPU_NATIVE_DECODE", "1" if native else "0"
        )
        # Pin the record-count cache into this test's tmp dir so the
        # tool's setdefault can't leak a deleted path into the process.
        monkeypatch.setenv("TFE_TPU_CACHE_DIR", str(tmp_path / "cache"))
        rc = host_input_bench.main(["--smoke", "--json", "--n=16"])
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        return rc, rec

    @pytest.mark.timeout(300)
    def test_smoke_record_native_vs_fallback(
        self, capsys, monkeypatch, tmp_path
    ):
        from tensorflow_examples_tpu import native

        rc, rec = self._run(capsys, monkeypatch, tmp_path, native=False)
        assert rc == 0, rec
        assert rec["metric"] == "host_input_pipeline_images_per_sec"
        assert rec["backend"] == "cpu" and rec["complete"] is True
        assert rec["decoder"] == "fallback"
        assert rec["identical"] is True  # parallel == sequential, bytewise
        assert rec["value"] > 0 and rec["sequential_images_per_sec"] > 0
        assert rec["fingerprint_tflops"] > 0
        assert rec["workers"] == 4 and rec["readers"] == 2
        assert rec["extras"][0]["metric"] == "host_input_seq_images_per_sec"
        if native.available("fastjpeg"):
            rc, rec = self._run(capsys, monkeypatch, tmp_path, native=True)
            assert rc == 0 and rec["decoder"] == "native"
            assert rec["identical"] is True and rec["complete"] is True

    def test_record_gates_against_cpu_floor(self, tmp_path):
        """The emitted record shape is gate-able by bench_gate against
        bench.FLOORS['cpu'] (synthetic values: deterministic verdicts
        on a box whose real throughput swings with ambient load)."""
        import bench
        import bench_gate

        floor, floor_fp = bench.FLOORS["cpu"][
            "host_input_pipeline_images_per_sec"
        ]

        def rec(value):
            return {
                "metric": "host_input_pipeline_images_per_sec",
                "value": value, "unit": "images/sec", "backend": "cpu",
                "fingerprint_tflops": floor_fp,
            }

        ok = tmp_path / "ok.json"
        ok.write_text(json.dumps(rec(floor * 1.5)))
        assert bench_gate.main([str(ok)]) == 0
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(rec(floor * 0.5)))
        assert bench_gate.main([str(bad)]) == 1

    def test_pipeline_only_extra_promoted_to_metric(self, tmp_path):
        """ISSUE 6: the buried pipeline_only_images_per_sec annotation
        becomes a first-class gated metric — from the parsed record AND
        from the torn-tail regex fallback."""
        import bench_gate

        doc = {
            "parsed": {
                "metric": "resnet50_examples_per_sec_per_chip",
                "value": 100.0, "backend": "tpu",
                "fingerprint_tflops": 2279.33,
                "extras": [
                    {
                        "metric": "resnet50_input_examples_per_sec_per_chip",
                        "value": 75.0,
                        "pipeline_only_images_per_sec": 474.6,
                    }
                ],
            }
        }
        p = tmp_path / "r.json"
        p.write_text(json.dumps(doc))
        recs = {r["metric"]: r for r in bench_gate.extract_records(str(p))}
        assert (
            recs["resnet50_input_pipeline_only_images_per_sec"]["value"]
            == 474.6
        )
        assert (
            recs["resnet50_input_pipeline_only_images_per_sec"][
                "fingerprint"
            ]
            == 2279.33
        )
        tail = (
            '{"metric": "resnet50_input_examples_per_sec_per_chip", '
            '"value": 75.0, "pipeline_only_images_per_sec": 474.6, '
            '"fingerprint_tflops_pre": 2279.33} "backend": "tpu"'
        )
        t = tmp_path / "t.json"
        t.write_text(json.dumps({"tail": tail}))
        recs = {r["metric"]: r for r in bench_gate.extract_records(str(t))}
        assert (
            recs["resnet50_input_pipeline_only_images_per_sec"]["value"]
            == 474.6
        )
        # banked trajectory (with the floored metric) still gates green
        assert bench_gate.main(
            [os.path.join(REPO, "BENCH_r0*.json")]
        ) == 0


@pytest.mark.serving
class TestServeBench:
    """The tier-1 serving smoke (ISSUE 5 CI satellite): stand the whole
    stack up on CPU, drive 20 concurrent requests over real HTTP via
    ``tools/serve_bench.py --smoke``, and bank a well-formed BENCH
    record with ZERO post-warmup recompiles."""

    @pytest.mark.timeout(300)
    def test_smoke_banks_wellformed_record(self, tmp_path, capsys):
        import serve_bench

        out = tmp_path / "serve_record.json"
        rc = serve_bench.main(
            ["--smoke", "--requests", "20", "--out", str(out)]
        )
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        # The stdout line is the same record (the BENCH driver contract:
        # last JSON line of stdout is the result).
        stdout_rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]
        )
        assert stdout_rec == rec
        assert rec["bench"] == "serving" and rec["backend"] == "cpu"
        assert rec["requests"] == 20 and rec["completed"] == 20
        assert rec["errors"] == 0 and rec["ok"] is True
        assert rec["transport"] == "http"
        # Zero-recompile steady state: exactly the warmed ladder.
        assert rec["post_warmup_recompiles"] == 0
        assert rec["compiles"] == rec["expected_compiles"]
        # Verified subset is token-identical to the unbatched reference.
        assert rec["verified"] == 3 and rec["verify_ok"] is True
        for key in ("req_per_s", "tok_per_s", "ttft_p95_ms",
                    "tpot_p95_ms", "e2e_p95_ms", "queue_wait_p95_ms"):
            assert isinstance(rec[key], (int, float)) and rec[key] > 0, key

    @pytest.mark.timeout(300)
    def test_smoke_slo_healthy_fires_zero_alerts(self, tmp_path):
        """ISSUE 19 CI satellite: a healthy smoke under ``--slo`` banks
        alert_count == 0 (the false-positive gate: generous default
        objectives must never fire on a healthy CPU run), full canary
        probe success, and an untouched error budget. The record is
        assembled BEFORE the probe phase, so probe traffic cannot
        pollute the banked percentiles."""
        import serve_bench

        out = tmp_path / "slo_record.json"
        rc = serve_bench.main(
            ["--smoke", "--requests", "12", "--out", str(out), "--slo"]
        )
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        assert rec["ok"] is True
        assert rec["requests"] == 12 and rec["completed"] == 12
        assert rec["alert_count"] == 0
        assert rec["alerts_firing"] == 0
        assert rec["probe_success_rate"] == 1.0
        assert rec["error_budget_remaining"] == 1.0
        # The probe phase re-checks the zero-recompile bar: synthetic
        # probes ride the SAME warmed ladder.
        assert rec["post_warmup_recompiles"] == 0
        # --slo needs the HTTP frontend (black-box probes): --inproc
        # and the special modes refuse it loudly.
        with pytest.raises(SystemExit):
            serve_bench.main(["--smoke", "--inproc", "--slo"])
        with pytest.raises(SystemExit):
            serve_bench.main(["--smoke", "--chaos", "--slo"])

    @pytest.mark.timeout(300)
    def test_smoke_trace_out_validates_and_renders(self, tmp_path, capsys):
        """ISSUE 18 CI satellite: ``--smoke --trace-out`` banks >= 1
        ``kind="trace"`` line that validates against schema v13, the
        record carries full coverage (bench drivers keep EVERY trace),
        and ``tools/trace_report.py --trace-id`` renders the span tree
        with its critical path."""
        import serve_bench
        import trace_report

        from tensorflow_examples_tpu.telemetry import schema

        traces = tmp_path / "traces.jsonl"
        out = tmp_path / "rec.json"
        rc = serve_bench.main([
            "--smoke", "--requests", "8", "--out", str(out),
            "--trace-out", str(traces),
        ])
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        # A measuring run samples nothing out: coverage is 1.0 and
        # every request left a trace.
        assert rec["traces_kept"] == 8
        assert rec["trace_coverage"] == 1.0
        with open(traces) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        assert len(lines) >= 1
        for line in lines:
            assert line["kind"] == "trace"
            assert (
                line["schema_version"] == schema.SERVING_SCHEMA_VERSION
            )
            problems = schema.validate_line(line)
            assert problems == [], problems
        tid = lines[0]["trace"]["trace_id"]
        capsys.readouterr()  # drop the bench's own stdout
        rc = trace_report.main(["--trace-id", tid, str(traces)])
        rendered = capsys.readouterr().out
        assert rc == 0
        assert tid in rendered
        assert "request" in rendered and "critical path:" in rendered
        # The replica's engine-phase spans made it across the wire
        # into the rendered tree.
        assert "decode_segment" in rendered

    @pytest.mark.timeout(300)
    def test_spec_decode_smoke_banks_ab_record(self, tmp_path):
        """ISSUE 11 satellite: ``--smoke --spec-decode K`` drives the
        SAME prompt-like prompts speculation-off then -on, banks a
        ``serve_spec`` record with the measured tpot_speedup /
        draft_hit_rate / accepted_per_step, and asserts every on-phase
        stream token-identical to its off-phase twin with zero
        post-warmup recompiles across both engines."""
        import serve_bench

        out = tmp_path / "spec_record.json"
        rc = serve_bench.main([
            "--smoke", "--spec-decode", "3", "--requests", "8",
            "--max-new-tokens", "16", "--concurrency", "4",
            "--out", str(out),
        ])
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        assert rec["bench"] == "serve_spec" and rec["spec_k"] == 3
        assert rec["errors"] == 0 and rec["ok"] is True
        assert rec["tokens_identical"] is True
        assert rec["verify_ok"] is True
        assert rec["post_warmup_recompiles"] == 0
        # The verify_k rungs are part of the warmed ladder.
        assert rec["expected_compiles"] > 0
        assert rec["tpot_speedup"] is not None and rec["tpot_speedup"] > 0
        assert 0.0 <= rec["draft_hit_rate"] <= 1.0
        assert rec["accepted_per_step"] >= 1.0
        assert rec["accepted_per_step_p50"] >= 1.0
        # Prompt-like traffic through the n-gram drafter must actually
        # accept drafts — otherwise the A/B measured nothing.
        assert rec["draft_hit_rate"] > 0.25

    @pytest.mark.timeout(300)
    def test_weight_dtype_smoke_banks_quant_record(self, tmp_path):
        """ISSUE 15 CI satellite: ``--smoke --weight-dtype int8``
        drives the SAME prompts through an f32 engine and a
        weight-quantized one, banks a ``serve_quant`` record with the
        measured HBM ratio (<= 0.35x — the ~4x claim), the
        first-token-exact + bounded-divergence verdict, and zero
        post-warmup recompiles across both engines."""
        import serve_bench

        out = tmp_path / "quant_record.json"
        rc = serve_bench.main([
            "--smoke", "--weight-dtype", "int8", "--requests", "10",
            "--out", str(out),
        ])
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        assert rec["bench"] == "serve_quant"
        assert rec["weight_dtype"] == "int8" and rec["weight_bits"] == 8
        assert rec["errors"] == 0 and rec["ok"] is True
        assert rec["first_token_exact"] is True
        assert rec["stream_agreement"] >= serve_bench.QUANT_AGREEMENT_FLOOR
        assert rec["verify_ok"] is True
        assert rec["post_warmup_recompiles"] == 0
        assert rec["hbm_bytes_per_replica"] <= (
            0.35 * rec["hbm_bytes_per_replica_f32"]
        )
        assert rec["hbm_ratio_vs_f32"] <= 0.35
        assert rec["tpot_speedup_quant"] is not None
        assert rec["tpot_speedup_quant"] > 0

    def test_bench_modes_are_mutually_exclusive(self, capsys):
        """Each mode banks its own record; combining two must be a
        loud usage error, never a silently-one-mode run."""
        import serve_bench

        with pytest.raises(SystemExit) as e:
            serve_bench.main(
                ["--smoke", "--weight-dtype", "int8",
                 "--spec-decode", "3"]
            )
        assert e.value.code == 2
        assert "don't compose" in capsys.readouterr().err

    @pytest.mark.timeout(300)
    def test_router_smoke_two_paged_replicas(self, tmp_path):
        """ISSUE 8 CI satellite: ``--smoke --router`` spins 2 in-proc
        PAGED replicas behind serving/router.py, drives real HTTP
        through the router, and banks a well-formed ``serve_router``
        record — verified tokens, >= 1 prefix-cache hit, and zero
        post-warmup recompiles summed over every replica."""
        import serve_bench

        out = tmp_path / "router_record.json"
        rc = serve_bench.main(
            ["--smoke", "--router", "--requests", "12",
             "--out", str(out)]
        )
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        assert rec["bench"] == "serve_router" and rec["replicas"] == 2
        assert rec["requests"] == 12 and rec["completed"] == 12
        assert rec["errors"] == 0 and rec["ok"] is True
        assert rec["transport"] == "router-http"
        # The paged tier: block size banked, >= 1 prefix-cache hit
        # from the shared-prefix prompt set.
        assert rec["kv_block_size"] == 16
        assert rec["prefix_hits"] >= 1
        assert 0 < rec["prefix_hit_rate"] <= 1
        # Zero-recompile steady state ACROSS the fleet.
        assert rec["post_warmup_recompiles"] == 0
        assert rec["compiles"] == rec["expected_compiles"]
        assert rec["verified"] == 3 and rec["verify_ok"] is True
        assert rec["router_dispatched"] >= 12
        assert rec["router_no_replica"] == 0
        for key in ("req_per_s", "tok_per_s", "ttft_p95_ms",
                    "tpot_p95_ms", "e2e_p95_ms"):
            assert isinstance(rec[key], (int, float)) and rec[key] > 0

    @pytest.mark.timeout(420)
    def test_affinity_ab_smoke_banks_record(self, tmp_path):
        """ISSUE 12 CI satellite: ``--smoke --router --affinity ab``
        drives the SAME shared-prefix traffic through an affinity-off
        fleet then an affinity-on one (deterministic sequential
        dispatch with manual probes) and banks the ``serve_affinity``
        record — the acceptance claim is prefix_hit_rate strictly
        GREATER with affinity on, verified streams token-identical,
        zero post-warmup recompiles across both fleets."""
        import serve_bench

        out = tmp_path / "affinity_record.json"
        rc = serve_bench.main(
            ["--smoke", "--router", "--affinity", "ab",
             "--requests", "12", "--out", str(out)]
        )
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        assert rec["bench"] == "serve_affinity"
        assert rec["errors"] == 0 and rec["ok"] is True
        # THE acceptance inequality, measured not sampled.
        assert (
            rec["prefix_hit_rate_affinity"]
            > rec["prefix_hit_rate_no_affinity"]
        )
        assert rec["affinity_hit_gain"] > 0
        assert rec["prefix_hits_on"] > rec["prefix_hits_off"]
        # Affinity dispatch actually fired (the counter, not luck).
        assert rec["affinity_dispatches"] >= 1
        assert rec["post_warmup_recompiles"] == 0
        assert rec["verified"] == 3 and rec["verify_ok"] is True
        # The shared-vs-cold TTFT split is banked for the record.
        for key in ("ttft_shared_p50_ms", "ttft_shared_p95_ms",
                    "ttft_cold_p50_ms", "ttft_cold_p95_ms"):
            assert isinstance(rec[key], (int, float)) and rec[key] > 0

    @pytest.mark.timeout(420)
    def test_chaos_smoke_banks_availability_record(self, tmp_path):
        """ISSUE 10 CI satellite: ``--smoke --chaos`` runs a
        SUPERVISED 2-replica paged fleet through a baseline phase and
        a crash-one-replica chaos phase, and banks the serve_chaos
        availability record: zero failed requests (error_rate 0 — the
        bench_gate smoke bound), >= 1 in-flight failover, a completed
        restart cycle, and the chaos p95 within the declared multiple
        of the fault-free baseline."""
        import serve_bench

        from tensorflow_examples_tpu.utils import faults as faults_mod

        out = tmp_path / "chaos_record.json"
        try:
            rc = serve_bench.main(
                ["--smoke", "--chaos", "--replicas", "2",
                 "--requests", "8", "--concurrency", "4",
                 "--out", str(out)]
            )
        finally:
            faults_mod.serve_clear()  # belt-and-braces for the suite
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        assert rec["bench"] == "serve_chaos" and rec["replicas"] == 2
        assert rec["ok"] is True
        # Availability: every request of BOTH phases completed even
        # though a replica was killed mid-decode.
        assert rec["errors"] == 0 and rec["error_rate"] == 0.0
        assert rec["faults_fired"] >= 1
        assert rec["failover_count"] >= 1
        # The supervisor completed one restart cycle and the fleet
        # ended green.
        assert rec["router_restarts"] == 1
        assert rec["fleet_restored"] is True
        # Tail latency bounded by the declared multiple.
        assert rec["p95_vs_baseline"] is not None
        assert rec["p95_vs_baseline"] <= rec["p95_budget"]
        # Zero post-warmup recompiles across survivors + the re-warmed
        # replica; verified subset token-identical through failover.
        assert rec["post_warmup_recompiles"] == 0
        assert rec["verified"] == 3 and rec["verify_ok"] is True

    @pytest.mark.timeout(420)
    def test_traffic_flash_smoke_banks_record(self, tmp_path):
        """ISSUE 13 CI satellite: ``--smoke --traffic flash`` drives
        the seeded 3x flash crowd open-loop through a 2-replica
        brownout-enabled fleet and banks the serve_traffic record:
        zero lost requests, zero interactive sheds, per-class TTFT
        p95s stamped, the flash/steady ratio within the declared
        budget, verified streams token-identical, zero post-warmup
        recompiles."""
        import serve_bench

        out = tmp_path / "traffic_flash.json"
        rc = serve_bench.main(
            ["--smoke", "--traffic", "flash", "--replicas", "2",
             "--out", str(out)]
        )
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        assert rec["bench"] == "serve_traffic"
        assert rec["traffic"] == "flash" and rec["ok"] is True
        # Shedding is split from real failures (ISSUE 13 satellite):
        # errors counts LOST requests only, and none were lost.
        assert rec["errors"] == 0 and rec["transport_errors"] == 0
        # All shedding (if any) landed on the batch class.
        assert rec["shed_interactive"] == 0
        assert rec["shed_rate_interactive"] == 0.0
        # The gate keys the record feeds bench_gate are stamped.
        for key in ("ttft_p95_interactive_ms", "ttft_p95_batch_ms",
                    "steady_ttft_p95_interactive_ms",
                    "flash_ttft_p95_interactive_ms"):
            assert isinstance(rec[key], (int, float)) and rec[key] > 0
        assert rec["flash_vs_steady_ttft"] is None or (
            rec["flash_vs_steady_ttft"] <= rec["flash_ttft_budget"]
        )
        assert rec["brownout_cleared"] is True
        assert rec["post_warmup_recompiles"] == 0
        assert rec["verified"] == 3 and rec["verify_ok"] is True
        # Replayability: the same seed makes a byte-identical schedule.
        a = serve_bench.make_traffic_schedule(
            "flash", 40, rate=25.0, vocab=211, max_len=64, max_new=8,
            seed=3,
        )
        b = serve_bench.make_traffic_schedule(
            "flash", 40, rate=25.0, vocab=211, max_len=64, max_new=8,
            seed=3,
        )
        assert a == b
        phases = {ev["phase"] for ev in a}
        assert phases == {"steady", "flash", "recover"}
        assert {ev["slo"] for ev in a} == {"interactive", "batch"}

    @pytest.mark.timeout(480)
    def test_traffic_ramp_smoke_scales_fleet(self, tmp_path):
        """ISSUE 13 autoscaler golden (smoke scale): ``--smoke
        --traffic ramp`` starts a 1-replica fleet under the
        telemetry-driven autoscaler; the ramp's peak forces at least
        one green-gated scale-up, scale-down drains back to 1 with
        zero lost requests, the record stamps scale_up_latency_s and
        p95_during_resize_ms, and the brownout ladder fully clears."""
        import serve_bench

        out = tmp_path / "traffic_ramp.json"
        rc = serve_bench.main(
            ["--smoke", "--traffic", "ramp", "--max-replicas", "3",
             "--out", str(out)]
        )
        assert rc == 0
        with open(out) as f:
            rec = json.load(f)
        assert rec["bench"] == "serve_traffic"
        assert rec["traffic"] == "ramp" and rec["ok"] is True
        # Zero failed requests across the whole resize cycle —
        # scale-down is drain-first, so nothing is ever lost.
        assert rec["errors"] == 0 and rec["transport_errors"] == 0
        # The fleet actually resized: up under the peak, back to min.
        assert rec["scale_ups"] >= 1 and rec["scale_downs"] >= 1
        assert rec["replicas_peak"] >= 2
        assert rec["replicas_final"] == 1
        # The autoscaler's own latency is a banked, gateable number.
        assert rec["scale_up_latency_s"] is not None
        assert rec["scale_up_latency_s"] > 0
        assert rec["brownout_cleared"] is True
        assert rec["post_warmup_recompiles"] == 0
        assert rec["verify_ok"] is True

    def test_make_prompts_spans_buckets(self):
        import serve_bench

        prompts = serve_bench.make_prompts(
            16, vocab=97, max_len=64, max_new=8
        )
        lengths = {len(p) for p in prompts}
        assert min(lengths) == 1 and max(lengths) == 56
        assert all(0 <= t < 97 for p in prompts for t in p)

    def test_make_prompts_shared_prefix(self):
        import serve_bench

        prompts = serve_bench.make_prompts(
            16, vocab=97, max_len=64, max_new=8, shared_prefix_every=4
        )
        shared = [prompts[i] for i in range(1, 16, 4)]
        pre = shared[0][:28]
        assert all(p[:28] == pre for p in shared)

    def test_requires_a_target(self):
        import serve_bench

        with pytest.raises(SystemExit):
            serve_bench.main([])


class TestTpuWatchMetrics:
    """ISSUE 18 satellite: ``tools/tpu_watch.sh --metrics`` against a
    ROUTER endpoint — the router serves the same /health //window
    //fleet surface as a replica, so the one watcher script covers
    both. Pinned: healthy polls print the health body and the
    kind=serving window summary; a gone endpoint after a healthy last
    probe means "run ended", exit 0."""

    @pytest.mark.timeout(120)
    def test_watch_polls_router_then_exits_zero_on_endpoint_gone(self):
        import time

        from tensorflow_examples_tpu.serving.router import (
            Router,
            RouterFrontend,
        )

        # No probe loop (start() not called): the hand-probed replica
        # stays eligible, so /health answers "ok": true. The watcher
        # only GETs — no engine needed behind the fake URL.
        router = Router(["http://127.0.0.1:9/"])
        router.replicas[0].probed = True
        rfront = RouterFrontend(router, port=0).start()
        proc = subprocess.Popen(
            ["bash", os.path.join(REPO, "tools", "tpu_watch.sh"),
             "--metrics", f"127.0.0.1:{rfront.port}",
             "--interval", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            time.sleep(3.5)  # a few healthy polls land
        finally:
            rfront.close()
            router.close()
        try:
            out, _ = proc.communicate(timeout=60)
        finally:
            proc.kill()
        assert proc.returncode == 0, out
        assert '"ok": true' in out  # health body echoed
        assert "kind=serving" in out  # /window summarized
        assert "endpoint gone: run ended" in out
        # Healthy-then-gone is a NORMAL end: the exit-reason pointer,
        # not a stall verdict.
        assert "exit reason is in the run dir" in out
        assert "STALLED" not in out


class TestSloWatch:
    """ISSUE 19 satellite: ``tools/slo_watch.py`` against a live router
    frontend. Pinned: the exit-code contract a deploy pipeline gates on
    (0 healthy, 1 while firing, 2 unreachable) and the rendered view —
    per-rule burn rates, and every firing alert with its severity and
    copy-paste exemplar command."""

    def _router(self):
        from tensorflow_examples_tpu.serving.router import (
            Router,
            RouterFrontend,
        )

        # No probe loop (start() not called): the hand-probed fake
        # replica stays eligible; the watcher only GETs /alerts +
        # /series, so no engine is needed behind the URL.
        router = Router(["http://127.0.0.1:9/"])
        router.replicas[0].probed = True
        rfront = RouterFrontend(router, port=0).start()
        return router, rfront

    @pytest.mark.timeout(120)
    def test_once_healthy_exits_zero(self, capsys):
        import slo_watch

        router, rfront = self._router()
        try:
            # One point in a default-series ring: the rollup tail
            # renders instruments the SLO rules burn on.
            router.series.record("router/e2e.p95", 0.01)
            rc = slo_watch.main(
                [f"127.0.0.1:{rfront.port}", "--once"]
            )
        finally:
            rfront.close()
            router.close()
        out = capsys.readouterr().out
        assert rc == 0
        assert "slo: 0 firing" in out
        assert "ok" in out and "FIRING" not in out
        assert "series" in out  # the /series rollup tail rendered

    @pytest.mark.timeout(120)
    def test_once_firing_exits_one_with_exemplar(self, capsys):
        import slo_watch

        from tensorflow_examples_tpu.telemetry.slo import (
            AlertEngine,
            SLOConfig,
            SLOObjective,
        )

        router, rfront = self._router()
        router.alerts = AlertEngine(
            SLOConfig(
                objectives=(SLOObjective(slo="interactive",
                                         e2e_p95_s=0.01,
                                         error_budget=0.01),),
                pending_for_s=0.0,
            ),
            registry=router.registry,
        )
        try:
            for _ in range(5):
                router.alerts.observe("interactive", e2e_s=1.0,
                                      trace_id="t-worst")
            router.alerts.evaluate()  # ok -> pending
            router.alerts.evaluate()  # pending -> firing (no dwell)
            rc = slo_watch.main(
                [f"http://127.0.0.1:{rfront.port}", "--once"]
            )
        finally:
            rfront.close()
            router.close()
        out = capsys.readouterr().out
        assert rc == 1
        assert "FIRING e2e_interactive" in out
        assert "--trace-id t-worst" in out  # the exemplar copy-paste

    @pytest.mark.timeout(120)
    def test_unreachable_exits_two(self, capsys):
        import slo_watch

        rc = slo_watch.main(
            ["127.0.0.1:9", "--once", "--timeout", "2"]
        )
        assert rc == 2


def test_readme_test_count_is_current():
    """README's `tests/` line states the suite size; keep it honest
    mechanically (VERDICT r4 weak #6) by comparing against pytest's own
    collection of this directory."""
    with open(os.path.join(REPO, "README.md")) as f:
        m = re.search(r"`tests/` — (\d+) tests", f.read())
    assert m, "README.md no longer carries the `tests/` — N tests line"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no axon-register start hang
    out = subprocess.run(
        [sys.executable, "-m", "pytest", os.path.join(REPO, "tests"),
         "--collect-only", "-q", "-p", "no:cacheprovider"],
        capture_output=True, text=True, timeout=300,
        env=env,
    )
    cm = re.search(r"(\d+) tests collected", out.stdout)
    assert cm, f"collection failed:\n{out.stdout[-2000:]}{out.stderr[-2000:]}"
    assert int(m.group(1)) == int(cm.group(1)), (
        f"README says {m.group(1)} tests, collection says {cm.group(1)} — "
        "update the README.md tests/ line"
    )


class TestTier1Budget:
    """The tier-1 wall guard (ISSUE 16 satellite): PR 12 noted the
    suite can exceed the 870 s CI wall. Heavy end-to-end goldens are a
    *budgeted allowlist* — a new test declaring a multi-minute timeout
    ceiling must either join the pinned list here (a reviewed wall
    spend) or go behind the ``slow`` marker (out of tier-1). This makes
    the budget regression loud at collection speed, with no subprocess
    suite run."""

    # Every tier-1 test allowed a timeout ceiling >= HEAVY_S, by
    # nodeid suffix. These are the load-bearing acceptance goldens the
    # marker policy (pyproject) says MUST run on every PR; growing
    # this list is a deliberate wall-budget decision, not a side
    # effect.
    HEAVY_S = 420
    ALLOWED_HEAVY = {
        "test_chaos.py::TestChaosGolden::test_kill_one_of_three_zero_failed_requests",
        "test_chaos.py::TestChaosGolden::test_kill_one_of_three_with_speculation_on",
        "test_chaos.py::TestChaosGolden::test_kill_prefill_replica_mid_handoff",
        "test_chaos.py::TestChaosGolden::test_decode_crash_yields_one_stitched_trace",
        "test_chaos.py::TestTakeoverGolden::test_killrouter_mid_stream_zero_lost_token_identical",
        "test_distributed.py::test_two_process_tp_matches_single_process",
        "test_resilience.py::test_fault_inject_tool_standalone",
        "test_tools.py::TestServeBench::test_affinity_ab_smoke_banks_record",
        "test_tools.py::TestServeBench::test_chaos_smoke_banks_availability_record",
        "test_tools.py::TestServeBench::test_traffic_flash_smoke_banks_record",
        "test_tools.py::TestServeBench::test_traffic_ramp_smoke_scales_fleet",
    }

    def _scan(self):
        """(nodeid_suffix, timeout_s, slow?) for every test function,
        via ast — decorator timeouts plus module pytestmark slow."""
        import ast

        found = []
        tests_dir = os.path.join(REPO, "tests")
        for fname in sorted(os.listdir(tests_dir)):
            if not (fname.startswith("test_") and fname.endswith(".py")):
                continue
            tree = ast.parse(
                open(os.path.join(tests_dir, fname)).read()
            )

            def mark_names(dec_list):
                names, timeouts = [], []
                for d in dec_list:
                    expr = d.func if isinstance(d, ast.Call) else d
                    name = ast.unparse(expr)
                    if not name.startswith("pytest.mark."):
                        continue
                    kind = name.split(".")[-1]
                    names.append(kind)
                    if (
                        kind == "timeout"
                        and isinstance(d, ast.Call)
                        and d.args
                        and isinstance(d.args[0], ast.Constant)
                    ):
                        timeouts.append(int(d.args[0].value))
                return names, timeouts

            module_slow = any(
                isinstance(node, ast.Assign)
                and any(
                    getattr(t, "id", None) == "pytestmark"
                    for t in node.targets
                )
                and "slow" in ast.unparse(node.value)
                for node in tree.body
            )
            for node in ast.walk(tree):
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if not node.name.startswith("test_"):
                    continue
                names, timeouts = mark_names(node.decorator_list)
                parents = [
                    c.name for c in ast.walk(tree)
                    if isinstance(c, ast.ClassDef)
                    and node in ast.walk(c)
                ]
                cls_slow = cls_timeouts = None
                for c in ast.walk(tree):
                    if isinstance(c, ast.ClassDef) and any(
                        n is node for n in ast.walk(c)
                    ):
                        cnames, ctimeouts = mark_names(
                            c.decorator_list
                        )
                        cls_slow = "slow" in cnames
                        cls_timeouts = ctimeouts
                suffix = fname + "::" + "::".join(
                    (parents[:1] or []) + [node.name]
                )
                slow = (
                    "slow" in names or bool(cls_slow) or module_slow
                )
                ceiling = max(timeouts + (cls_timeouts or []) + [0])
                found.append((suffix, ceiling, slow))
        return found

    def test_heavy_goldens_are_allowlisted_or_slow(self):
        scanned = self._scan()
        assert len(scanned) > 500  # the scan actually saw the suite
        offenders = [
            (suffix, ceiling)
            for suffix, ceiling, slow in scanned
            if ceiling >= self.HEAVY_S and not slow
            and suffix not in self.ALLOWED_HEAVY
            and not any(
                suffix.startswith(a.split("::")[0])
                and suffix.endswith(a.split("::")[-1])
                for a in self.ALLOWED_HEAVY
            )
        ]
        assert offenders == [], (
            f"tier-1 wall budget: {offenders} declare a >= "
            f"{self.HEAVY_S}s timeout ceiling without the 'slow' "
            "marker and outside the pinned allowlist — mark them slow "
            "or spend the budget explicitly in ALLOWED_HEAVY"
        )

    def test_allowlist_entries_exist(self):
        scanned = {s for s, _, _ in self._scan()}
        missing = {
            a for a in self.ALLOWED_HEAVY
            if not any(
                s.startswith(a.split("::")[0])
                and s.endswith(a.split("::")[-1])
                for s in scanned
            )
        }
        assert missing == set(), (
            f"stale tier-1 budget allowlist entries: {missing}"
        )
