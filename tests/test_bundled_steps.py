"""steps_per_launch (bundled train steps): K steps per device launch
via lax.scan — the TPU-native equivalent of the reference lineage's
Keras ``steps_per_execution`` (SURVEY.md §3(1) hot loop; the dispatch-
bound regime diagnosed in BASELINE.md round-4 is the motivation).

Parity contract under test: K scanned steps == K separate launches —
same RNG stream (keyed off state.step), same optimizer sequence
(incl. optax.MultiSteps grad accumulation) — so the bundled path may
only change WALL TIME, never the training trajectory.
"""

import numpy as np
import pytest

from tensorflow_examples_tpu.data.memory import train_iterator
from tensorflow_examples_tpu.data.prefetch import bundle_batches
from tensorflow_examples_tpu.data.sources import synthetic_images
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import mnist


def tiny_cfg(**kw):
    defaults = dict(
        device="cpu",
        global_batch_size=32,
        train_steps=8,
        log_every=8,
        learning_rate=1e-2,
        hidden=16,
        num_layers=1,
        dropout=0.0,
        precision="f32",
        checkpoint_every=0,
        workdir="",
    )
    defaults.update(kw)
    return mnist.MnistConfig(**defaults)


def _data(n=256):
    return synthetic_images(n=n, shape=(28, 28, 1), num_classes=10, seed=0)


def _params_vec(state):
    import jax

    return np.concatenate(
        [np.ravel(np.asarray(x)) for x in jax.tree.leaves(state.params)]
    )


def _run(cfg):
    trainer = Trainer(mnist.make_task(cfg), cfg)
    ds = _data()
    metrics = trainer.fit(
        train_iterator(ds, cfg.global_batch_size, seed=0),
        num_steps=cfg.train_steps,
    )
    return trainer, metrics


class TestBundledSteps:
    def test_bundle_matches_unbundled(self, devices):
        """8 steps as 2 launches of 4 == 8 launches of 1: identical final
        params (same data, same rng-by-step, same update sequence) and
        the same window-mean loss."""
        t1, m1 = _run(tiny_cfg())
        t4, m4 = _run(tiny_cfg(steps_per_launch=4))
        assert int(t1.state.step) == int(t4.state.step) == 8
        np.testing.assert_allclose(
            _params_vec(t1.state), _params_vec(t4.state), rtol=2e-5, atol=2e-6
        )
        assert abs(m1["loss"] - m4["loss"]) < 1e-4, (m1["loss"], m4["loss"])

    def test_bundle_with_grad_accum(self, devices):
        """optax.MultiSteps micro-steps tick per scan iteration: bundled
        and unbundled runs with grad_accum_steps=2 stay in lockstep."""
        t1, _ = _run(tiny_cfg(grad_accum_steps=2))
        t4, _ = _run(tiny_cfg(grad_accum_steps=2, steps_per_launch=4))
        np.testing.assert_allclose(
            _params_vec(t1.state), _params_vec(t4.state), rtol=2e-5, atol=2e-6
        )

    def test_cadence_validation(self, devices):
        cfg = tiny_cfg(steps_per_launch=3)  # 8 % 3 != 0
        trainer = Trainer(mnist.make_task(cfg), cfg)
        with pytest.raises(ValueError, match="steps_per_launch"):
            trainer.fit(
                train_iterator(_data(), cfg.global_batch_size, seed=0),
                num_steps=cfg.train_steps,
            )

    def test_resume_phase_validation(self, devices):
        """A k-unaligned resume point (checkpoint from an unbundled run)
        must be rejected even when the remaining SPAN divides by k —
        cadences fire on (step+1) % cadence and step+1 only visits
        start_step + i*k."""
        cfg = tiny_cfg(steps_per_launch=4, train_steps=14, log_every=0)
        trainer = Trainer(mnist.make_task(cfg), cfg)
        trainer.state = trainer.state.replace(step=6)  # span 8 % 4 == 0
        with pytest.raises(ValueError, match="start step"):
            trainer.fit(
                train_iterator(_data(), cfg.global_batch_size, seed=0),
                num_steps=cfg.train_steps,
            )

    def test_profile_trace_is_one_shot(self, devices, monkeypatch):
        """The profile window (steps ~10-20) captures exactly once; the
        chunked loop must not re-arm the trace after it stops (a re-arm
        would sync + restart the profiler every step for the rest of
        the run)."""
        import jax

        calls = {"start": 0, "stop": 0}
        monkeypatch.setattr(
            jax.profiler,
            "start_trace",
            lambda *a, **k: calls.__setitem__("start", calls["start"] + 1),
        )
        monkeypatch.setattr(
            jax.profiler,
            "stop_trace",
            lambda: calls.__setitem__("stop", calls["stop"] + 1),
        )
        cfg = tiny_cfg(train_steps=40, log_every=40, profile=True)
        _run(cfg)
        assert calls == {"start": 1, "stop": 1}, calls

    def test_checkpoint_at_bundle_boundary(self, devices, tmp_path):
        cfg = tiny_cfg(
            steps_per_launch=4,
            checkpoint_every=4,
            workdir=str(tmp_path),
            train_steps=8,
        )
        _run(cfg)
        from tensorflow_examples_tpu.train.checkpoint import CheckpointManager

        cfg2 = tiny_cfg(workdir=str(tmp_path))
        t2 = Trainer(mnist.make_task(cfg2), cfg2)
        restored = CheckpointManager(str(tmp_path)).restore_latest(t2.state)
        assert restored is not None and int(restored[1]) == 8


class TestBundledPipeline:
    def test_bundle_over_pp_step_matches_unbundled(self, devices):
        """lax.scan OVER the 1F1B pipeline step — a scan whose body is
        itself a shard_map'd scheduled program, the riskiest
        steps_per_launch composition — must reproduce the unbundled
        trajectory."""
        import jax

        from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
        from tensorflow_examples_tpu.workloads import gpt2

        def run(k):
            cfg = gpt2.Gpt2Config(
                vocab_size=64, seq_len=16, num_layers=2, num_heads=4,
                d_model=32, dropout=0.0, attention="xla",
                global_batch_size=16, train_steps=4, warmup_steps=1,
                learning_rate=3e-3, log_every=4, checkpoint_every=0,
                eval_every=0, precision="f32", num_microbatches=2,
                steps_per_launch=k,
            )
            mesh = create_mesh(MeshConfig(data=4, pipe=2))
            task = gpt2.make_task(cfg, mesh=mesh)
            trainer = Trainer(task, cfg, mesh=mesh)
            ds, _ = gpt2.datasets(cfg)
            m = trainer.fit(
                train_iterator(ds, cfg.global_batch_size, seed=0),
                num_steps=cfg.train_steps,
            )
            vec = np.concatenate(
                [
                    np.ravel(np.asarray(x))
                    for x in jax.tree.leaves(trainer.state.params)
                ]
            )
            return m["loss"], vec

        loss1, p1 = run(1)
        loss2, p2 = run(2)
        assert abs(loss1 - loss2) < 1e-4, (loss1, loss2)
        np.testing.assert_allclose(p1, p2, rtol=2e-5, atol=2e-6)


class TestBundleBatches:
    def test_stacks_k_batches(self):
        it = iter([{"x": np.full((2, 3), i)} for i in range(6)])
        out = list(bundle_batches(it, 3))
        assert len(out) == 2
        assert out[0]["x"].shape == (3, 2, 3)
        assert out[1]["x"][0, 0, 0] == 3

    def test_partial_bundle_raises(self):
        it = iter([{"x": np.zeros(2)} for _ in range(5)])
        gen = bundle_batches(it, 3)
        next(gen)
        with pytest.raises(ValueError, match="mid-bundle"):
            next(gen)

    def test_clean_exhaustion(self):
        assert list(bundle_batches(iter([]), 4)) == []
