"""Cache-aware fleet scheduling (ISSUE 12): prefix-affinity chain
keys/digests, chunked prefill admission, disaggregated prefill/decode
KV-page handoff, schema v9.

The load-bearing tests:

* :class:`TestChunkedPrefillGolden` — a long cold prompt admitted
  mid-load is split into block-aligned chunks that INTERLEAVE with the
  co-scheduled requests' decode steps (structurally asserted), never
  stalls decode longer than ~one chunk (pinned budget), and the chunked
  stream is token-identical to the unchunked reference (the golden
  replay makes that free).
* :class:`TestHandoffGolden` — a prompt prefilled on one engine,
  exported as serialized KV pages, imported on ANOTHER engine, and
  decoded there is token-identical to the reference; over HTTP the
  /prefill -> /resume pair carries the same contract, and a geometry
  mismatch is a loud 400.

Everything else is deterministic unit coverage: content chain keys
(stable across pool resets — the property cross-replica affinity
relies on), the chunk planner, the page codec, the router's
affinity-vs-load pick, and the v9 schema pin mirroring every prior
version bump's.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from tensorflow_examples_tpu.models import transformer
from tensorflow_examples_tpu.serving import scheduler
from tensorflow_examples_tpu.serving.batcher import (
    ContinuousBatcher,
    Request,
)
from tensorflow_examples_tpu.serving.engine import (
    InferenceEngine,
    ServeConfig,
)
from tensorflow_examples_tpu.serving.frontend import ServingFrontend
from tensorflow_examples_tpu.serving.paged_kv import PagedKVPool
from tensorflow_examples_tpu.serving.router import Router, RouterConfig
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


TINY_MODEL = dict(
    vocab_size=211,
    max_len=64,
    num_layers=1,
    num_heads=2,
    d_model=16,
    dropout=0.0,
    attention="xla",
)


def _build_engine(*, max_len=64, **serve_kw):
    import jax
    import jax.numpy as jnp

    base = dict(TINY_MODEL)
    base["max_len"] = max_len
    cfg = transformer.TransformerConfig(**base)
    model = transformer.Transformer(cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32)
    )["params"]
    kw = dict(
        max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
        kv_block_size=8, max_delay_s=0.0, request_timeout_s=60.0,
    )
    kw.update(serve_kw)
    return InferenceEngine(
        cfg, params, cfg=ServeConfig(**kw), registry=MetricsRegistry()
    )


# ------------------------------------------------------------ chain keys


class TestChainKeys:
    def test_deterministic_and_parent_sensitive(self):
        a = scheduler.chain_key("", [1, 2, 3, 4])
        assert a == scheduler.chain_key("", [1, 2, 3, 4])
        assert a != scheduler.chain_key(a, [1, 2, 3, 4])
        assert a != scheduler.chain_key("", [1, 2, 3, 5])

    def test_prompt_chain_caps_below_length(self):
        """Exactly prefix_lookup's cap: the tail keeps >= 1 token, so
        a block-aligned prompt publishes one less key than blocks."""
        assert len(scheduler.prompt_chain_keys(list(range(32)), 8)) == 3
        assert len(scheduler.prompt_chain_keys(list(range(33)), 8)) == 4
        assert scheduler.prompt_chain_keys([1, 2], 8) == []

    def test_affinity_walk_stops_at_first_miss(self):
        keys = scheduler.prompt_chain_keys(list(range(40)), 8)
        assert scheduler.affinity_blocks(keys, set(keys)) == 4
        assert scheduler.affinity_blocks(keys, set(keys[:2])) == 2
        # A matching deep key without its ancestors is unreachable.
        assert scheduler.affinity_blocks(keys, {keys[3]}) == 0


class TestChunkPlan:
    def test_block_aligned_spans_cover_tail(self):
        spans = scheduler.plan_chunks(100, 16, 32, 8)
        assert spans == [(16, 48), (48, 80), (80, 100)]
        assert scheduler.plan_chunks(48, 0, 16, 8) == [
            (0, 16), (16, 32), (32, 48)
        ]
        assert scheduler.plan_chunks(16, 16, 16, 8) == []

    def test_rejects_misaligned_inputs(self):
        with pytest.raises(ValueError, match="multiple of block_size"):
            scheduler.plan_chunks(64, 0, 12, 8)
        with pytest.raises(ValueError, match="block-aligned"):
            scheduler.plan_chunks(64, 3, 16, 8)
        with pytest.raises(ValueError, match="exceeds prompt length"):
            scheduler.plan_chunks(16, 24, 16, 8)


class TestPageCodec:
    def _payload(self, dtype=np.float32, scales=False):
        rng = np.random.default_rng(0)
        shape = (2, 3, 2, 8, 4)
        arrays = {
            "k": rng.standard_normal(shape).astype(dtype),
            "v": rng.standard_normal(shape).astype(dtype),
        }
        if scales:
            arrays["k_scale"] = rng.standard_normal(shape[:-1]).astype(
                np.float32
            )
            arrays["v_scale"] = rng.standard_normal(shape[:-1]).astype(
                np.float32
            )
        meta = dict(block_size=8, num_layers=2, num_heads=2,
                    head_dim=4, length=20, kv_bits=32)
        return meta, arrays

    def test_roundtrip_through_json(self):
        meta, arrays = self._payload()
        wire = json.loads(json.dumps(scheduler.encode_pages(meta, arrays)))
        meta2, arrays2 = scheduler.decode_pages(wire)
        assert meta2 == meta
        for name in arrays:
            assert np.array_equal(arrays2[name], arrays[name])

    def test_int8_scales_ride_along(self):
        meta, arrays = self._payload(dtype=np.int8, scales=True)
        meta["kv_bits"] = 8
        wire = json.loads(json.dumps(scheduler.encode_pages(meta, arrays)))
        _, arrays2 = scheduler.decode_pages(wire)
        assert arrays2["k"].dtype == np.int8
        assert np.array_equal(arrays2["k_scale"], arrays["k_scale"])

    def test_malformations_are_loud(self):
        meta, arrays = self._payload()
        wire = scheduler.encode_pages(meta, arrays)
        with pytest.raises(ValueError, match="wire version"):
            scheduler.decode_pages(dict(wire, version=99))
        bad = json.loads(json.dumps(wire))
        bad["arrays"]["k"]["shape"] = [1, 1, 1, 1, 1]
        with pytest.raises(ValueError, match="does not match shape"):
            scheduler.decode_pages(bad)
        bad = json.loads(json.dumps(wire))
        bad["arrays"]["v"]["data"] = "@@not-base64@@"
        with pytest.raises(ValueError, match="malformed pages array"):
            scheduler.decode_pages(bad)
        with pytest.raises(ValueError, match="missing the k/v"):
            scheduler.decode_pages(dict(wire, arrays={}))
        with pytest.raises(ValueError, match="JSON object"):
            scheduler.decode_pages([1, 2])


# ---------------------------------------------------------- pool digest


class TestPrefixDigest:
    def _pool(self):
        return PagedKVPool(
            num_layers=1, num_slots=2, num_heads=1, max_len=64,
            head_dim=4, block_size=8, registry=MetricsRegistry(),
        )

    def _publish(self, pool, prompt):
        slot = pool.alloc()
        blocks = pool.alloc_blocks(-(-len(prompt) // pool.block_size))
        pool.assign(slot, blocks)
        pool.insert_prefix(slot, prompt)
        return slot

    def test_digest_matches_prompt_chain(self):
        pool = self._pool()
        prompt = list(range(20))  # 2 full blocks + partial tail
        self._publish(pool, prompt)
        d = pool.prefix_digest()
        assert d["blocks"] == 2 and d["chains"] == 1
        keys = scheduler.prompt_chain_keys(prompt, 8)
        assert scheduler.affinity_blocks(keys, set(d["keys"])) == 2
        # A different prompt matches nothing.
        other = scheduler.prompt_chain_keys(list(range(50, 70)), 8)
        assert scheduler.affinity_blocks(other, set(d["keys"])) == 0

    def test_digest_stable_across_reset(self):
        """The satellite pin: content-addressed keys survive reset()
        (fresh physical ids, same tokens -> same digest) — the property
        that makes cross-replica and restart-spanning affinity sound."""
        pool = self._pool()
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2, 3]
        self._publish(pool, prompt)
        before = pool.prefix_digest()
        pool.reset()
        assert pool.prefix_digest()["keys"] == []
        self._publish(pool, prompt)
        after = pool.prefix_digest()
        assert after["keys"] == before["keys"]
        assert after["blocks"] == before["blocks"]

    def test_two_chains_counted(self):
        pool = self._pool()
        self._publish(pool, list(range(20)))
        self._publish(pool, list(range(100, 120)))
        d = pool.prefix_digest()
        assert d["blocks"] == 4 and d["chains"] == 2

    def test_digest_capped_shallowest_first(self):
        pool = self._pool()
        prompt = list(range(33))  # 4 full blocks published
        self._publish(pool, prompt)
        d = pool.prefix_digest(max_keys=2)
        keys = scheduler.prompt_chain_keys(prompt, 8)
        # The cap keeps the shallow (most reusable) links.
        assert d["keys"] == keys[:2]

    def test_reallocate_drops_digest(self):
        pool = self._pool()
        self._publish(pool, list(range(20)))
        pool.reallocate()
        assert pool.prefix_digest() == {
            "keys": [], "blocks": 0, "chains": 0, "truncated": False,
        }


# -------------------------------------------------------- affinity pick


class TestAffinityPick:
    """Router dispatch-policy units — no sockets, states set by hand
    (the pattern of test_router.TestPick)."""

    def _router(self, **cfg_kw):
        r = Router(
            ["http://a:1", "http://b:2"],
            cfg=RouterConfig(**cfg_kw) if cfg_kw else None,
        )
        for rep in r.replicas:
            rep.probed = True
            rep.block_size = 8
        return r

    def test_prefers_longest_cached_chain(self):
        r = self._router()
        a, b = r.replicas
        prompt = list(range(40))
        keys = scheduler.prompt_chain_keys(prompt, 8)
        a.prefix_digest = frozenset(keys[:1])
        b.prefix_digest = frozenset(keys[:3])
        assert r.pick(prompt=prompt) is b
        assert (
            r.registry.counter_values()["router/affinity_hits_total"]
            == 1
        )

    def test_affinity_never_starves_a_hot_replica(self):
        """The load guard: the chain-holder only wins while its load
        score is within affinity_load_gap of the least-loaded."""
        r = self._router()
        a, b = r.replicas
        prompt = list(range(40))
        b.prefix_digest = frozenset(
            scheduler.prompt_chain_keys(prompt, 8)
        )
        b.queue_depth = r.cfg.affinity_load_gap + 1.0
        assert r.pick(prompt=prompt) is a
        b.queue_depth = r.cfg.affinity_load_gap - 0.5
        assert r.pick(prompt=prompt) is b

    def test_affinity_disabled_falls_back_to_load(self):
        r = self._router(prefix_affinity=False)
        a, b = r.replicas
        prompt = list(range(40))
        b.prefix_digest = frozenset(
            scheduler.prompt_chain_keys(prompt, 8)
        )
        b.dispatched = 1
        assert r.pick(prompt=prompt) is a

    def test_no_digest_no_preference(self):
        r = self._router()
        picked = {r.pick(prompt=list(range(40))).url for _ in range(2)}
        assert len(picked) == 2  # plain dispatched-tiebreak rotation

    def test_role_filter_mixed_serves_everything(self):
        r = self._router()
        a, b = r.replicas
        a.role, b.role = "prefill", "decode"
        assert r.pick(role="prefill") is a
        assert r.pick(role="decode") is b
        assert r.pick() is not None  # full path matches any role
        a.role = "mixed"
        assert r.pick(role="decode") in (a, b)

    def test_snapshot_carries_scheduling_fields(self):
        r = self._router()
        snap = r.replicas[0].snapshot_locked()
        assert snap["role"] == "mixed"
        assert snap["prefix_blocks"] == 0
        assert snap["prefix_chains"] == 0


# ----------------------------------------------- chunked prefill golden

# A chunked admission may stall co-scheduled decode steps by AT MOST
# ~one chunk: the pinned budget is a generous multiple of the longest
# single chunk actually measured (CI rigs are load-noisy; the claim is
# "bounded by a chunk", not "free").
CHUNK_STALL_FACTOR = 8.0
CHUNK_STALL_SLACK_S = 0.25


@pytest.fixture(scope="module")
def chunk_engine():
    """One warmed chunk-admission engine shared by the chunked-prefill
    goldens (the AOT warmup dominates; tests reset the pool and assert
    counter DELTAS so sharing is sound)."""
    engine = _build_engine(
        max_len=128, prefill_chunk_tokens=16, kv_bucket_floor=32,
    )
    engine.warmup()
    yield engine
    assert engine.pool.active_slots == 0, "a test leaked KV slots"


class TestChunkedPrefillGolden:
    @pytest.mark.timeout(300)
    def test_long_cold_prompt_interleaves_and_stays_token_identical(
        self, chunk_engine
    ):
        """ISSUE 12 (b): a long cold prompt admitted while short
        requests decode is split into block-aligned chunks, every
        chunk-to-chunk gap contains a decode step (the structural
        interleave claim), no decode gap exceeds the pinned
        one-chunk budget, and the chunked stream is token-identical
        to the unchunked reference replay."""
        engine = chunk_engine
        engine.pool.reset()
        counters0 = dict(engine.registry.counter_values())
        calls = []
        lock = threading.Lock()
        orig_step = engine.prefill_step
        orig_decode = engine.decode

        def step(state):
            t0 = time.perf_counter()
            out = orig_step(state)
            with lock:
                calls.append(("chunk", time.perf_counter() - t0,
                              time.perf_counter()))
            return out

        def decode(entries):
            out = orig_decode(entries)
            with lock:
                calls.append(("decode", 0.0, time.perf_counter()))
            return out

        engine.prefill_step = step
        engine.decode = decode
        batcher = ContinuousBatcher(engine).start()
        rng = np.random.default_rng(11)
        long_prompt = [int(t) for t in rng.integers(0, 211, 100)]
        try:
            shorts = [
                batcher.submit(Request(
                    prompt=[5 + i, 6, 7], max_new_tokens=24, seed=i,
                ))
                for i in range(2)
            ]
            deadline = time.monotonic() + 30
            while not batcher._active and time.monotonic() < deadline:
                time.sleep(0.002)
            assert batcher._active, "short requests never started"
            long_fut = batcher.submit(Request(
                prompt=long_prompt, max_new_tokens=4, seed=7,
                temperature=0.7,
            ))
            results = [f.result(timeout=120) for f in shorts]
            long_res = long_fut.result(timeout=120)
        finally:
            batcher.close(drain=True)
            engine.prefill_step = orig_step
            engine.decode = orig_decode
        # Token-identical to the unbatched reference — chunking is an
        # admission policy, never a numerics change.
        assert long_res.tokens == engine.reference_generate(
            long_prompt, max_new=4, seed=7, temperature=0.7,
        )
        for i, res in enumerate(results):
            assert res.tokens == engine.reference_generate(
                [5 + i, 6, 7], max_new=24, seed=i,
            )
        counters = engine.registry.counter_values()
        assert counters["serving/chunked_prefills"] - counters0.get(
            "serving/chunked_prefills", 0
        ) == 1
        # 100 cold tokens at chunk 16 -> 7 chunks (6 full + ragged).
        assert counters["serving/prefill_chunks"] - counters0.get(
            "serving/prefill_chunks", 0
        ) == 7
        chunk_idx = [i for i, c in enumerate(calls) if c[0] == "chunk"]
        assert len(chunk_idx) == 7
        # Structural interleave: a decode step sits between every
        # consecutive pair of chunks (one chunk per loop iteration,
        # decode after — the shorts outlive the whole chunked prefill
        # by construction).
        for i, j in zip(chunk_idx, chunk_idx[1:]):
            between = [calls[k][0] for k in range(i + 1, j)]
            assert "decode" in between, (
                f"chunks {i}->{j} ran back-to-back: {calls}"
            )
        # The stall bound: during the chunk phase, no decode-to-decode
        # gap exceeds the pinned budget of ~one chunk.
        max_chunk = max(c[1] for c in calls if c[0] == "chunk")
        decode_times = [
            c[2] for c in calls[chunk_idx[0]:chunk_idx[-1] + 2]
            if c[0] == "decode"
        ]
        gaps = [b - a for a, b in zip(decode_times, decode_times[1:])]
        budget = CHUNK_STALL_FACTOR * max_chunk + CHUNK_STALL_SLACK_S
        assert max(gaps) <= budget, (max(gaps), budget)
        assert engine.post_warmup_recompiles() == 0

    @pytest.mark.timeout(300)
    def test_chunked_prefill_reuses_cached_prefix(self, chunk_engine):
        """A chunked admission still takes the prefix-cache hit: the
        cached context never re-chunks, only the cold tail does."""
        engine = chunk_engine
        engine.pool.reset()
        chunks0 = engine.registry.counter_values().get(
            "serving/prefill_chunks", 0
        )
        batcher = ContinuousBatcher(engine).start()
        rng = np.random.default_rng(12)
        prefix = [int(t) for t in rng.integers(0, 211, 64)]
        try:
            first = batcher.submit(Request(
                prompt=prefix + [1, 2], max_new_tokens=2, seed=0,
            )).result(timeout=120)
            chunks_cold = engine.registry.counter_values()[
                "serving/prefill_chunks"
            ] - chunks0
            second = batcher.submit(Request(
                prompt=prefix + [3, 4, 5], max_new_tokens=2, seed=1,
            )).result(timeout=120)
        finally:
            batcher.close(drain=True)
        chunks_total = engine.registry.counter_values()[
            "serving/prefill_chunks"
        ] - chunks0
        # First admission chunked the cold 66 tokens (5 chunks); the
        # second hit 64 cached tokens, so its whole cold tail is the
        # 3-token remainder — ONE span, one extend call, exactly what
        # the plain prefix-hit path would have run.
        assert chunks_cold == 5
        assert chunks_total == chunks_cold + 1
        assert engine.pool.prefix_hits >= 1
        assert first.tokens == engine.reference_generate(
            prefix + [1, 2], max_new=2, seed=0
        )
        assert second.tokens == engine.reference_generate(
            prefix + [3, 4, 5], max_new=2, seed=1
        )

    @pytest.mark.timeout(300)
    def test_deadline_expiry_abandons_remaining_chunks(
        self, chunk_engine
    ):
        """A chunked prefill whose deadline passes mid-plan is
        abandoned (504, serving/expired_total) instead of stalling
        everyone else's decode steps for chunks that can deliver
        nothing."""
        engine = chunk_engine
        engine.pool.reset()
        chunks0 = engine.registry.counter_values().get(
            "serving/prefill_chunks", 0
        )
        orig_step = engine.prefill_step

        def slow_step(state):
            time.sleep(0.05)
            return orig_step(state)

        engine.prefill_step = slow_step
        batcher = ContinuousBatcher(engine).start()
        rng = np.random.default_rng(13)
        long_prompt = [int(t) for t in rng.integers(0, 211, 100)]
        try:
            fut = batcher.submit(Request(
                prompt=long_prompt, max_new_tokens=4, seed=0,
                deadline_s=0.08,
            ))
            from tensorflow_examples_tpu.serving.batcher import (
                DeadlineExceeded,
            )

            with pytest.raises(DeadlineExceeded, match="chunked"):
                fut.result(timeout=60)
        finally:
            batcher.close(drain=True)
            engine.prefill_step = orig_step
        chunks = engine.registry.counter_values().get(
            "serving/prefill_chunks", 0
        ) - chunks0
        # Far fewer than the 7 chunks a full admission runs.
        assert chunks < 7
        assert engine.registry.counter_values().get(
            "serving/expired_total", 0
        ) >= 1
        assert engine.pool.active_slots == 0

    def test_chunk_requires_paged_pool(self):
        with pytest.raises(ValueError, match="paged pool"):
            _build_engine(kv_block_size=0, prefill_chunk_tokens=16)

    def test_chunk_must_be_block_multiple(self):
        with pytest.raises(ValueError, match="multiple of kv_block"):
            _build_engine(kv_block_size=8, prefill_chunk_tokens=12)

    def test_role_validated(self):
        with pytest.raises(ValueError, match="role="):
            _build_engine(role="gpu")


# ------------------------------------------------------- handoff golden


@pytest.fixture(scope="module")
def handoff_engines():
    """One donor + one importer (same params — the disagg contract
    assumes one model behind every role). NOT warmed: the handoff
    goldens pin token identity and recompile-freedom, not latency, so
    lazy first-use compilation (1 per rung = within the sentinel
    allowance) keeps the module cheap."""
    donor = _build_engine()
    importer = _build_engine()
    yield donor, importer
    assert donor.pool.active_slots == 0
    assert importer.pool.active_slots == 0


class TestHandoffGolden:
    @pytest.mark.timeout(300)
    def test_imported_pages_decode_token_identical(
        self, handoff_engines
    ):
        """Engine-level ISSUE 12 (c): prefill on A, export, import on
        B, decode on B — the stream is token-identical to the
        reference (fp32 pages roundtrip bitwise)."""
        donor, importer = handoff_engines
        rng = np.random.default_rng(21)
        prompt = [int(t) for t in rng.integers(0, 211, 37)]
        slot = donor.pool.alloc()
        first, _ = donor.prefill(slot, prompt, seed=5, temperature=0.7)
        pages = json.loads(json.dumps(
            donor.export_kv_pages(slot, prompt)
        ))
        donor.pool.free(slot)
        batcher = ContinuousBatcher(importer).start()
        try:
            res = batcher.submit(Request(
                prompt=prompt, max_new_tokens=5, seed=5,
                temperature=0.7, kind="resume", pages=pages,
                first_token=int(first),
            )).result(timeout=120)
        finally:
            batcher.close(drain=True)
        assert res.tokens == importer.reference_generate(
            prompt, max_new=5, seed=5, temperature=0.7
        )
        assert importer.post_warmup_recompiles() == 0
        # The import seeded the importer's prefix cache: the next
        # shared-prefix admission hits locally.
        hits_before = importer.pool.prefix_hits
        slot = importer.pool.alloc()
        importer.prefill(slot, prompt[:16] + [9], seed=0)
        importer.pool.free(slot)
        assert importer.pool.prefix_hits == hits_before + 1

    @pytest.mark.timeout(300)
    def test_geometry_mismatch_rejected(self, handoff_engines):
        donor, importer = handoff_engines
        prompt = list(range(20))
        slot = donor.pool.alloc()
        donor.prefill(slot, prompt, seed=0)
        pages = donor.export_kv_pages(slot, prompt)
        donor.pool.free(slot)
        wrong = json.loads(json.dumps(pages))
        wrong["block_size"] = 16
        slot = importer.pool.alloc()
        try:
            with pytest.raises(ValueError, match="geometry mismatch"):
                importer.import_kv_pages(slot, wrong, prompt)
            with pytest.raises(ValueError, match="pages cover"):
                importer.import_kv_pages(slot, pages, prompt + [1])
        finally:
            importer.pool.free(slot)

    @pytest.mark.timeout(300)
    def test_prefill_resume_over_http(self, handoff_engines):
        """The wire pair: POST /prefill on a prefill-role stack, ship
        the reply's pages to POST /resume on a decode-role stack, and
        the resumed stream is token-identical to the reference."""
        donor, importer = handoff_engines
        stacks = []
        for engine in (donor, importer):
            batcher = ContinuousBatcher(engine).start()
            frontend = ServingFrontend(batcher, port=0).start()
            stacks.append((batcher, frontend))
        rng = np.random.default_rng(22)
        prompt = [int(t) for t in rng.integers(0, 211, 29)]

        def post(frontend, path, body):
            req = urllib.request.Request(
                frontend.url(path), data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read() or b"{}")

        try:
            status, pre = post(
                stacks[0][1], "/prefill",
                {"prompt": prompt, "seed": 3, "temperature": 0.7},
            )
            assert status == 200, pre
            assert isinstance(pre["first_token"], int)
            assert isinstance(pre["pages"], dict)
            status, out = post(
                stacks[1][1], "/resume",
                {"prompt": prompt, "max_new_tokens": 4, "seed": 3,
                 "temperature": 0.7, "pages": pre["pages"],
                 "first_token": pre["first_token"]},
            )
            assert status == 200, out
            assert out["tokens"] == importer.reference_generate(
                prompt, max_new=4, seed=3, temperature=0.7
            )
            # Malformed resume bodies are 400s, never 500s.
            status, err = post(
                stacks[1][1], "/resume",
                {"prompt": prompt, "first_token": 1},
            )
            assert status == 400 and "pages" in err["error"]
        finally:
            for batcher, frontend in stacks:
                batcher.close(drain=True)
                frontend.close()

    @pytest.mark.timeout(300)
    def test_int8_pages_roundtrip(self):
        """int8 pools hand off int8 payloads + blockwise scales; the
        importer's continuation matches the donor's own continuation
        exactly (same quantized cache bytes on both sides — one engine
        plays both roles, importing into a different slot, which
        exercises the same wire + scatter path as a cross-process
        handoff)."""
        engine = _build_engine(kv_dtype="int8")  # lazy compiles: only
        #                                          the 2 rungs it uses
        rng = np.random.default_rng(23)
        prompt = [int(t) for t in rng.integers(0, 211, 21)]
        d_slot = engine.pool.alloc()
        first, _ = engine.prefill(d_slot, prompt, seed=9)
        pages = json.loads(json.dumps(
            engine.export_kv_pages(d_slot, prompt)
        ))
        assert pages["kv_bits"] == 8
        assert "k_scale" in pages["arrays"]
        i_slot = engine.pool.alloc()
        engine.import_kv_pages(i_slot, pages, prompt)
        donor_stream, importer_stream = [], []
        d_tok = i_tok = int(first)
        for _ in range(4):
            d_tok = engine.decode(
                [(d_slot, d_tok, 9, 0.0, 0)]
            )[d_slot]
            i_tok = engine.decode(
                [(i_slot, i_tok, 9, 0.0, 0)]
            )[i_slot]
            donor_stream.append(d_tok)
            importer_stream.append(i_tok)
        engine.pool.free(d_slot)
        engine.pool.free(i_slot)
        assert importer_stream == donor_stream

    def test_handoff_requires_paged_pool(self):
        engine = _build_engine(kv_block_size=0)
        batcher = ContinuousBatcher(engine)
        fut = batcher.submit(Request(
            prompt=[1, 2, 3], kind="prefill",
        ))
        with pytest.raises(ValueError, match="paged KV pool"):
            fut.result(timeout=5)


# ------------------------------------------ streaming delta (ISSUE 15)


class TestDeltaHandoff:
    """PR 11 follow-up: /prefill -> /resume ships only the pages the
    importer's prefix cache doesn't already hold — the digest exchange
    rides the handoff request as ``skip_tokens`` and the pages'
    ``start_block`` meta."""

    def test_start_block_codec_roundtrip_and_malformations(self):
        meta = dict(block_size=8, num_layers=1, num_heads=2, head_dim=8,
                    length=20, kv_bits=32, start_block=1)
        arrays = {"k": np.ones((1, 2, 2, 8, 8), np.float32),
                  "v": np.zeros((1, 2, 2, 8, 8), np.float32)}
        payload = json.loads(json.dumps(
            scheduler.encode_pages(meta, arrays)
        ))
        got_meta, got_arrays = scheduler.decode_pages(payload)
        assert got_meta["start_block"] == 1
        assert got_arrays["k"].shape == (1, 2, 2, 8, 8)
        # Absent start_block reads as 0 (pre-delta payloads): neither
        # the wire nor the parsed meta carry the key.
        no_skip = scheduler.encode_pages(
            {**meta, "start_block": 0}, arrays
        )
        assert "start_block" not in no_skip
        assert "start_block" not in scheduler.decode_pages(no_skip)[0]
        with pytest.raises(ValueError, match="start_block"):
            scheduler.encode_pages({**meta, "start_block": -1}, arrays)
        bad = dict(payload)
        bad["start_block"] = -2
        with pytest.raises(ValueError, match="start_block"):
            scheduler.decode_pages(bad)
        whole = dict(payload)
        whole["start_block"] = 5  # 5 * 8 >= length 20
        with pytest.raises(ValueError, match="skips the whole"):
            scheduler.decode_pages(whole)

    @pytest.mark.timeout(300)
    def test_delta_import_token_identical_when_prefix_held(self):
        """Engine level: the importer already caches the shared prefix
        (an earlier full handoff); a delta export skipping it imports
        clean and the continued stream is token-identical — while the
        wire payload carries strictly fewer blocks."""
        donor = _build_engine()
        importer = _build_engine()
        rng = np.random.default_rng(31)
        prompt = [int(t) for t in rng.integers(0, 211, 37)]
        # Round 1: full handoff seeds the importer's prefix cache.
        slot = donor.pool.alloc()
        first, _ = donor.prefill(slot, prompt, seed=5)
        full = donor.export_kv_pages(slot, prompt)
        donor.pool.free(slot)
        i_slot = importer.pool.alloc()
        importer.import_kv_pages(i_slot, full, prompt)
        importer.pool.free(i_slot)
        # Round 2: same prompt, digest says the importer holds
        # (len-1)//bs * bs = 32 leading tokens.
        slot = donor.pool.alloc()
        first2, _ = donor.prefill(slot, prompt, seed=5)
        delta = json.loads(json.dumps(
            donor.export_kv_pages(slot, prompt, skip_tokens=32)
        ))
        donor.pool.free(slot)
        assert first2 == first
        assert delta["start_block"] == 4
        nb_full = len(full["arrays"]["k"]["data"])
        nb_delta = len(delta["arrays"]["k"]["data"])
        assert nb_delta < nb_full // 3  # 1 of 5 blocks on the wire
        exported = donor.registry.counter_values()
        assert exported["serving/kv_pages_delta_skipped"] == 4
        i_slot = importer.pool.alloc()
        importer.import_kv_pages(i_slot, delta, prompt)
        stream = []
        tok = int(first)
        for _ in range(4):
            tok = importer.decode([(i_slot, tok, 5, 0.0, 0)])[i_slot]
            stream.append(tok)
        importer.pool.free(i_slot)
        ref = importer.reference_generate(prompt, max_new=5, seed=5)
        assert [int(first)] + stream == ref

    @pytest.mark.timeout(300)
    def test_cold_importer_rejects_delta_loudly(self):
        """A delta payload landing on a replica whose prefix cache
        does NOT cover the skip (probe-stale digest) is a loud
        ValueError (-> 400 -> router full-path fallback), never a torn
        cache."""
        donor = _build_engine()
        cold = _build_engine()
        prompt = list(range(40))
        slot = donor.pool.alloc()
        donor.prefill(slot, prompt, seed=0)
        delta = donor.export_kv_pages(slot, prompt, skip_tokens=16)
        donor.pool.free(slot)
        i_slot = cold.pool.alloc()
        try:
            with pytest.raises(ValueError, match="prefix cache covers"):
                cold.import_kv_pages(i_slot, delta, prompt)
        finally:
            cold.pool.free(i_slot)

    @pytest.mark.timeout(300)
    def test_skip_tokens_over_http_prefill(self):
        """The wire surface: /prefill accepts skip_tokens and the
        reply's pages carry start_block; junk skip_tokens is a 400."""
        engine = _build_engine()
        batcher = ContinuousBatcher(engine).start()
        frontend = ServingFrontend(batcher, port=0).start()

        def post(path, body):
            req = urllib.request.Request(
                frontend.url(path), data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read() or b"{}")

        prompt = list(range(30))
        try:
            status, pre = post(
                "/prefill", {"prompt": prompt, "skip_tokens": 16},
            )
            assert status == 200, pre
            assert pre["pages"]["start_block"] == 2
            status, err = post(
                "/prefill", {"prompt": prompt, "skip_tokens": -1},
            )
            assert status == 400
            # skip_tokens is a prefill-leg field only.
            status, err = post(
                "/generate", {"prompt": prompt, "skip_tokens": 8},
            )
            assert status == 400 and "unknown" in err["error"]
        finally:
            batcher.close(drain=True)
            frontend.close()

    def test_failed_delta_handoff_counts_no_savings(self):
        """router/handoff_delta_tokens_total only ticks on COMPLETED
        handoffs: a handoff whose legs fail (dead replicas here) falls
        back to the full path having saved nothing."""
        from tensorflow_examples_tpu.serving.router import RouterConfig

        router = Router(
            ["http://127.0.0.1:1", "http://127.0.0.1:2"],
            cfg=RouterConfig(max_retries=0, retry_budget_s=0.1,
                             retry_backoff_s=0.0),
        )
        pre, dec = router.replicas
        for r, role in ((pre, "prefill"), (dec, "decode")):
            r.probed = True
            r.role = role
            r.block_size = 8
        prompt = list(range(33))
        dec.prefix_digest = frozenset(
            scheduler.prompt_chain_keys(prompt, 8)
        )
        out = router._handle_disagg({"prompt": prompt}, prompt, {})
        assert out is None  # both legs dead -> full-path fallback
        counters = router.registry.counter_values()
        assert counters.get("router/handoff_delta_tokens_total", 0) == 0

    def test_router_digest_exchange_is_conservative_minimum(self):
        """_decode_cached_tokens: the skip is the MINIMUM over eligible
        decode-serving replicas (safe whichever one the resume lands
        on), and 0 the moment any candidate has no digest."""
        router = Router(["http://a", "http://b"])
        a, b = router.replicas
        for r, role in ((a, "decode"), (b, "mixed")):
            r.probed = True
            r.role = role
            r.block_size = 8
        prompt = list(range(33))
        keys = scheduler.prompt_chain_keys(prompt, 8)
        a.prefix_digest = frozenset(keys)       # holds 4 blocks
        b.prefix_digest = frozenset(keys[:2])   # holds 2 blocks
        assert router._decode_cached_tokens(prompt, {}) == 16
        b.prefix_digest = frozenset()
        assert router._decode_cached_tokens(prompt, {}) == 0
        b.role = "prefill"  # not a resume candidate anymore
        assert router._decode_cached_tokens(prompt, {}) == 32


# ------------------------------------------- bloom digest (ISSUE 15)


class TestBloomDigest:
    def test_roundtrip_no_false_negatives(self):
        keys = [scheduler.chain_key("", [i]) for i in range(300)]
        bloom = scheduler.decode_bloom(json.loads(json.dumps(
            scheduler.encode_bloom(keys)
        )))
        assert len(bloom) == 300
        assert all(k in bloom for k in keys), "bloom NEVER false-negs"

    def test_false_positive_rate_sane(self):
        keys = [scheduler.chain_key("", [i]) for i in range(500)]
        bloom = scheduler.decode_bloom(scheduler.encode_bloom(keys))
        probes = [
            scheduler.chain_key("x", [i]) for i in range(2000)
        ]
        fp = sum(p in bloom for p in probes) / len(probes)
        assert fp < 0.05, f"false-positive rate {fp} out of spec"

    def test_empty_filter_is_falsy_and_matches_nothing(self):
        bloom = scheduler.decode_bloom(scheduler.encode_bloom([]))
        assert not bloom
        assert scheduler.chain_key("", [1]) not in bloom

    def test_malformed_payloads_are_loud(self):
        good = scheduler.encode_bloom(["ab"])
        for mutate in (
            lambda p: p.pop("bits"),
            lambda p: p.__setitem__("bits", "###"),
            lambda p: p.__setitem__("m", 7),
            lambda p: p.__setitem__("m", scheduler.BLOOM_MAX_BITS * 2),
            lambda p: p.__setitem__("k", 0),
            lambda p: p.__setitem__("n", -1),
        ):
            bad = dict(good)
            mutate(bad)
            with pytest.raises(ValueError):
                scheduler.decode_bloom(bad)
        with pytest.raises(ValueError):
            scheduler.decode_bloom("not a dict")

    def test_affinity_blocks_walks_a_bloom(self):
        prompt = list(range(40))
        keys = scheduler.prompt_chain_keys(prompt, 8)
        bloom = scheduler.decode_bloom(scheduler.encode_bloom(keys[:3]))
        got = scheduler.affinity_blocks(keys, bloom)
        assert got >= 3  # exact is 3; a false positive may extend it

    def test_pool_publishes_bloom_when_truncated(self):
        pool = PagedKVPool(
            num_layers=1, num_slots=2, num_heads=1, max_len=32,
            head_dim=4, block_size=8, registry=MetricsRegistry(),
        )
        for i in range(6):
            slot = pool.alloc()
            prompt = [i * 100 + j for j in range(16)]
            total = -(-len(prompt) // 8)
            blocks = pool.alloc_blocks(total)
            pool.assign(slot, blocks)
            pool.lengths[slot] = len(prompt)
            pool.insert_prefix(slot, prompt)
            pool.free(slot)
        full = pool.prefix_digest()
        assert "bloom" not in full  # under the cap: exact keys suffice
        capped = pool.prefix_digest(max_keys=4)
        assert capped["truncated"]
        bloom = scheduler.decode_bloom(capped["bloom"])
        # The bloom covers EVERY chain key, including the shed tail.
        assert len(bloom) == full["blocks"]
        assert all(k in bloom for k in full["keys"])

    def test_bloom_cached_until_published_set_changes(self):
        """The encoded filter is built once per cache generation (and
        outside the lock): an unchanged cache serves the same object
        to every probe; publishing a new chain invalidates it."""
        pool = PagedKVPool(
            num_layers=1, num_slots=2, num_heads=1, max_len=32,
            head_dim=4, block_size=8, registry=MetricsRegistry(),
        )

        def publish(base):
            slot = pool.alloc()
            prompt = [base + j for j in range(16)]
            blocks = pool.alloc_blocks(2)
            pool.assign(slot, blocks)
            pool.lengths[slot] = 16
            pool.insert_prefix(slot, prompt)
            pool.free(slot)

        publish(0)
        publish(100)
        b1 = pool.prefix_digest(max_keys=1)["bloom"]
        b2 = pool.prefix_digest(max_keys=1)["bloom"]
        assert b1 is b2, "unchanged cache must reuse the encoded bloom"
        publish(200)
        b3 = pool.prefix_digest(max_keys=1)["bloom"]
        assert b3 is not b1
        key = scheduler.chain_key("", [200 + j for j in range(8)])
        assert key in scheduler.decode_bloom(b3)

    def test_router_probe_prefers_bloom_over_truncated_list(self):
        router = Router(["http://a"])
        (a,) = router.replicas
        prompt = list(range(40))
        keys = scheduler.prompt_chain_keys(prompt, 8)
        payload = scheduler.encode_bloom(keys)

        def fake_get(url, timeout):
            return 200, {
                "ok": True,
                "prefix_block_size": 8,
                "prefix_digest": keys[:1],  # capped list
                "digest_truncated": True,
                "prefix_bloom": payload,
            }

        from tensorflow_examples_tpu.serving import router as router_mod

        orig = router_mod._get_json
        router_mod._get_json = fake_get
        try:
            router.probe_once()
        finally:
            router_mod._get_json = orig
        assert isinstance(a.prefix_digest, scheduler.BloomDigest)
        assert scheduler.affinity_blocks(keys, a.prefix_digest) >= len(
            keys
        ) - 0
        # A malformed bloom keeps the key list instead of failing the
        # probe sweep.
        def bad_get(url, timeout):
            return 200, {
                "ok": True,
                "prefix_block_size": 8,
                "prefix_digest": keys[:1],
                "prefix_bloom": {"m": 7, "k": 1, "n": 1, "bits": "x"},
            }

        router_mod._get_json = bad_get
        try:
            router.probe_once()
        finally:
            router_mod._get_json = orig
        assert a.prefix_digest == frozenset(keys[:1])


# -------------------------------------------------------------- schema


class TestSchemaV9:
    def test_paged_stats_line_carries_prefix_summary(self):
        engine = _build_engine()
        batcher = ContinuousBatcher(engine)
        line = json.loads(json.dumps(batcher.stats_line()))
        assert line["schema_version"] == schema.SERVING_SCHEMA_VERSION
        assert line["schema_version"] == 14
        assert schema.validate_line(line) == []
        assert line["serving"]["prefix_blocks"] == 0
        assert line["serving"]["prefix_chains"] == 0

    def test_v9_keys_flagged_on_older_versions(self):
        """Satellite pin: prefix_blocks/prefix_chains are v9-only — a
        'v8' (or older) serving line carrying them is a mislabeled v9
        line, same rule as every earlier bump."""
        base = {
            "schema_version": 9, "kind": "serving", "step": 1,
            "time_unix": 1.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {}, "counters": {}, "gauges": {}, "derived": {},
            "serving": {
                "active_requests": 0, "queue_depth": 0, "slots": 4,
                "kv_occupancy": 0.0, "post_warmup_recompiles": 0,
                "draining": 0, "prefix_blocks": 3, "prefix_chains": 1,
            },
        }
        assert schema.validate_line(base) == []
        for version in (4, 5, 6, 7, 8):
            stale = dict(base, schema_version=version)
            problems = schema.validate_line(stale)
            for key in schema.SERVING_KEYS_V9:
                assert any(
                    f"v9 serving key '{key}'" in p for p in problems
                ), (version, key, problems)

    def test_dense_line_carries_no_v9_keys(self):
        engine = _build_engine(kv_block_size=0)
        batcher = ContinuousBatcher(engine)
        line = batcher.stats_line()
        for key in schema.SERVING_KEYS_V9:
            assert key not in line["serving"]
