"""Speculative decoding (ISSUE 11): drafter units, acceptance rule,
token-identical goldens with speculation ON, accounting, exhaustion
degradation, and the schema-v8 serving keys.

The load-bearing tests are the goldens: mixed greedy AND
temperature-sampled requests through the continuous batcher with
``spec_decode_k > 0`` must come out token-identical to the engine's
unbatched reference replay — on the dense AND the paged pool. That is
the determinism contract: speculation buys TPOT, it never changes one
token (acceptance consumes the per-request ``fold_in`` key stream per
POSITION, so which rows ship cannot change what any position draws).
"""

import json
import os
import sys

import numpy as np
import pytest

from tensorflow_examples_tpu.models import transformer
from tensorflow_examples_tpu.serving.batcher import (
    ContinuousBatcher,
    Request,
)
from tensorflow_examples_tpu.serving.engine import (
    InferenceEngine,
    ServeConfig,
)
from tensorflow_examples_tpu.serving.speculative import (
    NgramDraft,
    accept_drafts,
    make_draft,
)
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def tiny_cfg(**kw):
    import serve_bench  # needs the tools path above

    base = dict(serve_bench.SMOKE_MODEL)
    base.update(kw)
    return transformer.TransformerConfig(**base)


def _tiny_params(cfg):
    import jax
    import jax.numpy as jnp

    model = transformer.Transformer(cfg)
    return model.init(
        {"params": jax.random.PRNGKey(1)}, jnp.zeros((1, 8), jnp.int32)
    )["params"]


def _spec_engine(*, params=None, cfg=None, **serve_kw):
    cfg = cfg or tiny_cfg()
    kw = dict(
        max_slots=4, prefill_bucket_floor=16, kv_bucket_floor=32,
        max_delay_s=0.002, spec_decode_k=3,
    )
    kw.update(serve_kw)
    engine = InferenceEngine(
        cfg,
        params if params is not None else _tiny_params(cfg),
        cfg=ServeConfig(**kw),
        registry=MetricsRegistry(),
    )
    counts = engine.warmup()
    assert sum(counts.values()) == engine.expected_compiles()
    return engine


@pytest.fixture(scope="module")
def spec_engine():
    """One warmed DENSE engine with spec_decode_k=3 for the module."""
    engine = _spec_engine()
    yield engine
    assert engine.pool.active_slots == 0, "a test leaked KV slots"


@pytest.fixture(scope="module")
def paged_spec_engine():
    """The paged twin (block 8, same ladder floors)."""
    engine = _spec_engine(kv_block_size=8)
    yield engine
    assert engine.pool.active_slots == 0, "a test leaked KV slots"


def _spec_requests(n, cfg, *, max_new=6, seed=123):
    """Mixed prompt-like (tiled motif) and adversarial (random)
    prompts, a third sampled rather than greedy — speculation must be
    invisible on BOTH traffic shapes."""
    rng = np.random.default_rng(seed)
    cap = cfg.max_len - max_new
    reqs = []
    for i in range(n):
        ln = int(rng.integers(4, cap + 1))
        if i % 2 == 0:
            motif = [int(t) for t in rng.integers(0, cfg.vocab_size, 4)]
            prompt = (motif * (ln // 4 + 1))[:ln]
        else:
            prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, ln)]
        temp, top_k = ((0.0, 0), (0.9, 0), (1.0, 7))[i % 3]
        reqs.append(Request(
            prompt=prompt, max_new_tokens=max_new, temperature=temp,
            top_k=top_k, seed=i,
        ))
    return reqs


# ------------------------------------------------------------ drafter


class TestNgramDraft:
    def test_repeated_motif_proposes_continuation(self):
        d = NgramDraft(max_ngram=3)
        d.begin(0, [1, 2, 3, 1, 2, 3, 1, 2])
        assert d.propose(0, 3) == [3, 1, 2]

    def test_cycle_extrapolates_past_context_end(self):
        # A period-1 loop must fill the whole window, not one token.
        d = NgramDraft(max_ngram=3)
        d.begin(0, [9, 5, 5, 5])
        assert d.propose(0, 4) == [5, 5, 5, 5]
        d2 = NgramDraft(max_ngram=2)
        d2.begin(1, [7, 8, 7, 8])
        assert d2.propose(1, 4) == [7, 8, 7, 8]

    def test_no_repeat_proposes_nothing(self):
        d = NgramDraft(max_ngram=3)
        d.begin(0, [1, 2, 3, 4, 5, 6])
        assert d.propose(0, 4) == []

    def test_longest_ngram_wins(self):
        # [1,2] occurs twice with different continuations; the 2-gram
        # match (continuation 7) must beat the 1-gram's.
        d = NgramDraft(max_ngram=3)
        d.begin(0, [1, 2, 7, 4, 2, 9, 1, 2])
        assert d.propose(0, 1) == [7]

    def test_extend_and_end_lifecycle(self):
        d = NgramDraft(max_ngram=2)
        d.begin(3, [1, 2])
        d.extend(3, [1, 2])
        assert d.propose(3, 2) == [1, 2]
        d.end(3)
        d.end(3)  # idempotent
        assert 3 not in d._ctx

    def test_deterministic(self):
        ctx = list(np.random.default_rng(0).integers(0, 50, 40))
        a, b = NgramDraft(), NgramDraft()
        a.begin(0, ctx)
        b.begin(0, ctx)
        assert a.propose(0, 5) == b.propose(0, 5)

    def test_make_draft_factory(self):
        assert isinstance(make_draft(ServeConfig()), NgramDraft)
        with pytest.raises(ValueError, match="draft"):
            make_draft(ServeConfig(draft="llama-draft"))


class TestAcceptance:
    def test_all_agree_commits_k_plus_one(self):
        assert accept_drafts([5, 6, 7], [5, 6, 7, 8], limit=10) \
            == [5, 6, 7, 8]

    def test_first_disagreement_stops(self):
        assert accept_drafts([5, 9, 7], [5, 6, 7, 8], limit=10) == [5, 6]

    def test_no_drafts_commits_one(self):
        assert accept_drafts([], [4, 0, 0, 0], limit=10) == [4]

    def test_limit_caps_committed_rows(self):
        assert accept_drafts([5, 6, 7], [5, 6, 7, 8], limit=2) == [5, 6]
        assert accept_drafts([5, 6, 7], [5, 6, 7, 8], limit=1) == [5]


# ------------------------------------------------------------- goldens


class TestSpeculativeGolden:
    @pytest.mark.timeout(300)
    def test_dense_token_identical_to_reference(self, spec_engine):
        """THE ISSUE 11 golden (dense): 10 mixed requests — greedy AND
        temperature sampling — through the batcher with speculation on,
        token-identical to the unbatched reference, zero post-warmup
        recompiles, and real draft acceptance happened."""
        eng = spec_engine
        reqs = _spec_requests(10, eng.model_cfg)
        compiles_before = dict(eng.sentinel.compile_counts())
        batcher = ContinuousBatcher(eng).start()
        try:
            futs = [batcher.submit(r) for r in reqs]
            results = [f.result(timeout=120) for f in futs]
        finally:
            batcher.close(drain=True)
        for req, res in zip(reqs, results):
            ref = eng.reference_generate(
                req.prompt, max_new=req.max_new_tokens, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            assert res.tokens == ref, (
                f"speculative != reference for prompt_len="
                f"{len(req.prompt)} temp={req.temperature}"
            )
        counters = eng.registry.counter_values()
        assert counters.get("serving/spec_accepted_total", 0) >= 1, (
            "motif prompts must take real draft acceptances or the "
            "golden only covered the degenerate path"
        )
        assert eng.sentinel.compile_counts() == compiles_before
        assert eng.post_warmup_recompiles() == 0

    @pytest.mark.timeout(300)
    def test_paged_token_identical_to_reference(self, paged_spec_engine):
        """The paged twin: same contract through block tables (the
        spec window crosses block boundaries at block 8)."""
        eng = paged_spec_engine
        reqs = _spec_requests(10, eng.model_cfg, seed=321)
        batcher = ContinuousBatcher(eng).start()
        try:
            futs = [batcher.submit(r) for r in reqs]
            results = [f.result(timeout=120) for f in futs]
        finally:
            batcher.close(drain=True)
        for req, res in zip(reqs, results):
            ref = eng.reference_generate(
                req.prompt, max_new=req.max_new_tokens, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            assert res.tokens == ref
        counters = eng.registry.counter_values()
        assert counters.get("serving/spec_accepted_total", 0) >= 1
        assert eng.post_warmup_recompiles() == 0
        assert eng.pool.used_bytes() == 0

    @pytest.mark.timeout(120)
    def test_eos_mid_window_truncates_exactly(self, spec_engine):
        """Tokens past eos inside an accepted verify window are
        discarded — the stream equals the non-speculative one, which
        stops at eos."""
        eng = spec_engine
        prompt = [9, 3, 5, 9, 3, 5, 9, 3]
        ref = eng.reference_generate(
            prompt, max_new=8, seed=4, temperature=1.0
        )
        j = next(
            i for i, t in enumerate(ref) if i and t not in ref[:i]
        )
        batcher = ContinuousBatcher(eng).start()
        try:
            res = batcher.submit(Request(
                prompt=prompt, max_new_tokens=8, eos_id=ref[j],
                temperature=1.0, seed=4,
            )).result(timeout=60)
        finally:
            batcher.close(drain=True)
        assert res.tokens == ref[:j + 1]
        assert res.truncated is None

    @pytest.mark.timeout(120)
    def test_accounting_committed_equals_stream(self):
        """Acceptance-counter accounting: every committed token is a
        stream token — decode_tokens == sum(len(stream) - 1) (the
        first token comes from prefill), and accepted <= drafted."""
        eng = _spec_engine()
        reqs = _spec_requests(6, eng.model_cfg, max_new=8, seed=77)
        batcher = ContinuousBatcher(eng).start()
        try:
            futs = [batcher.submit(r) for r in reqs]
            results = [f.result(timeout=120) for f in futs]
        finally:
            batcher.close(drain=True)
        counters = eng.registry.counter_values()
        stream_tokens = sum(len(res.tokens) for res in results)
        assert counters["serving/decode_tokens"] \
            == stream_tokens - len(reqs)
        drafted = counters.get("serving/spec_drafted_total", 0)
        accepted = counters.get("serving/spec_accepted_total", 0)
        assert 0 <= accepted <= drafted
        # Verify steps commit exactly request_steps + accepted tokens;
        # draft-less steps fall back to plain decode, so <=.
        assert counters["serving/spec_request_steps"] + accepted \
            <= counters["serving/decode_tokens"]
        # Per-request accounting (Result.spec_*): the fleet counters
        # are exactly the per-request sums, and each stream's length is
        # its decode commits (prefill token + accepted + plain steps).
        assert sum(r.spec_drafted for r in results) == drafted
        assert sum(r.spec_accepted for r in results) == accepted
        for res in results:
            assert 0 <= res.spec_accepted <= res.spec_drafted
            assert res.spec_accepted <= len(res.tokens) - 1

    @pytest.mark.timeout(120)
    def test_paged_exhaustion_shrinks_window_before_shedding(self):
        """A pool that cannot back the full spec window but CAN back
        one more row must shrink the window (serve slower), not fail
        the request — speculation never reduces availability."""
        cfg = tiny_cfg()
        eng = InferenceEngine(
            cfg, _tiny_params(cfg),
            cfg=ServeConfig(
                max_slots=2, prefill_bucket_floor=16, kv_bucket_floor=32,
                max_delay_s=0.0, kv_block_size=8, spec_decode_k=3,
                kv_blocks=4,  # 3 usable blocks = 24 rows
            ),
            registry=MetricsRegistry(),
        )
        eng.warmup()
        batcher = ContinuousBatcher(eng).start()
        try:
            # 16-token prompt (2 blocks) + 7 generated tops out INSIDE
            # the third block: the +3 spec lookahead would want a 4th
            # block the pool cannot give near the end.
            res = batcher.submit(Request(
                prompt=list(range(100, 116)), max_new_tokens=7, seed=1,
            )).result(timeout=60)
        finally:
            batcher.close(drain=True)
        assert res.tokens == eng.reference_generate(
            list(range(100, 116)), max_new=7, seed=1
        )
        assert eng.post_warmup_recompiles() == 0


# ------------------------------------------------------------ schema v8


class TestSchemaV8:
    @pytest.mark.timeout(120)
    def test_stats_line_carries_spec_keys_and_validates(self, spec_engine):
        eng = spec_engine
        batcher = ContinuousBatcher(eng).start()
        try:
            batcher.submit(Request(
                prompt=[5, 6, 5, 6, 5, 6], max_new_tokens=6, seed=2,
            )).result(timeout=60)
            line = json.loads(json.dumps(batcher.stats_line()))
        finally:
            batcher.close(drain=True)
        assert line["schema_version"] == schema.SERVING_SCHEMA_VERSION == 14
        assert schema.validate_line(line) == []
        serving = line["serving"]
        assert serving["spec_k"] == 3
        assert 0.0 <= serving["draft_hit_rate"] <= 1.0
        assert serving["accepted_per_step"] >= 1.0

    def test_v8_keys_flagged_on_older_versions(self):
        """Satellite: the speculation keys are v8-only — a 'v7' (or
        older) serving line carrying them is a mislabeled v8 line."""
        base = {
            "schema_version": 8, "kind": "serving", "step": 1,
            "time_unix": 1.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {}, "counters": {}, "gauges": {}, "derived": {},
            "serving": {
                "active_requests": 0, "queue_depth": 0, "slots": 4,
                "kv_occupancy": 0.0, "post_warmup_recompiles": 0,
                "draining": 0, "spec_k": 3, "draft_hit_rate": 0.5,
                "accepted_per_step": 2.0,
            },
        }
        assert schema.validate_line(base) == []
        for version in (4, 5, 6, 7):
            stale = dict(base, schema_version=version)
            problems = schema.validate_line(stale)
            for key in schema.SERVING_KEYS_V8:
                assert any(
                    f"v8 serving key '{key}'" in p for p in problems
                ), (version, key, problems)

    def test_spec_free_line_carries_no_v8_keys(self):
        """A NON-speculative batcher's line must not leak the keys."""
        cfg = tiny_cfg()
        eng = InferenceEngine(
            cfg, _tiny_params(cfg),
            cfg=ServeConfig(max_slots=2, prefill_bucket_floor=16,
                            kv_bucket_floor=32),
            registry=MetricsRegistry(),
        )
        batcher = ContinuousBatcher(eng)
        line = batcher.stats_line()
        for key in schema.SERVING_KEYS_V8:
            assert key not in line["serving"]


# ------------------------------------------------------- config guards


class TestSpecConfig:
    def test_negative_k_rejected(self):
        cfg = tiny_cfg()
        with pytest.raises(ValueError, match="spec_decode_k"):
            InferenceEngine(
                cfg, _tiny_params(cfg),
                cfg=ServeConfig(spec_decode_k=-1),
                registry=MetricsRegistry(),
            )

    def test_window_must_fit_prefill_floor(self):
        cfg = tiny_cfg()
        with pytest.raises(ValueError, match="prefill_bucket_floor"):
            InferenceEngine(
                cfg, _tiny_params(cfg),
                cfg=ServeConfig(spec_decode_k=16,
                                prefill_bucket_floor=16),
                registry=MetricsRegistry(),
            )

    def test_paged_flash_requires_paged_pool(self):
        cfg = tiny_cfg()
        with pytest.raises(ValueError, match="paged_flash"):
            InferenceEngine(
                cfg, _tiny_params(cfg),
                cfg=ServeConfig(attention="paged_flash"),
                registry=MetricsRegistry(),
            )
