"""SLO engine, time-series store, canary prober (ISSUE 19).

The load-bearing contracts:

* :class:`TestAlertEngine` — the burn-rate unit matrix on an
  injectable clock: multi-window gating (a spike that burns only the
  fast window cannot fire), pending -> firing hysteresis, flap
  suppression, sustained-health resolve, the worst-offender exemplar,
  and the fsynced ``kind="alert"`` sink round-trip.
* :class:`TestProbeExclusion` — the probe tag's exclusion contract on
  a REAL router + journal: probe traffic leaves the journal dedupe
  window, the tenant intent log, ``router/requests_total`` and the
  organic AlertEngine feed untouched.
* :class:`TestSchemaV14Ritual` — the versioning ritual for the v14
  additions (the alert kind and the serving summary keys are forbidden
  on every line that predates them).

Replicas here are device-free fake engines behind real HTTP frontends
(the test_router idiom); the real-fleet tier is ``serve_bench --smoke
--slo`` in tests/test_tools.py and the chaos alert golden in
tests/test_chaos.py.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from tensorflow_examples_tpu.serving import kv_cache
from tensorflow_examples_tpu.serving.batcher import ContinuousBatcher
from tensorflow_examples_tpu.serving.engine import ServeConfig
from tensorflow_examples_tpu.serving.frontend import ServingFrontend
from tensorflow_examples_tpu.serving.prober import (
    CanaryProber,
    fleet_targets,
)
from tensorflow_examples_tpu.serving.router import (
    Router,
    RouterConfig,
    RouterFrontend,
)
from tensorflow_examples_tpu.telemetry import schema, slo
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry
from tensorflow_examples_tpu.telemetry.slo import (
    AlertEngine,
    SLOConfig,
    SLOObjective,
)
from tensorflow_examples_tpu.telemetry.timeseries import TimeSeriesStore

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


class _FakeEngine:
    """Deterministic device-free engine (the test_router idiom): token
    stream is prompt[-1]+1, +2, ... — every replica serves identical
    output, so known-answer probes agree across the fleet."""

    def __init__(self, *, max_slots=4, max_queue=32, max_len=64):
        self.cfg = ServeConfig(
            max_slots=max_slots, max_queue=max_queue, max_delay_s=0.0,
            request_timeout_s=30.0,
        )
        import serve_bench

        from tensorflow_examples_tpu.models import transformer

        base = dict(serve_bench.SMOKE_MODEL)
        base["max_len"] = max_len
        self.model_cfg = transformer.TransformerConfig(**base)
        self.registry = MetricsRegistry()
        self.pool = kv_cache.KVCachePool(
            num_layers=1, num_slots=max_slots, num_heads=1,
            max_len=max_len, head_dim=2, registry=self.registry,
        )
        self.warmed = True

    def post_warmup_recompiles(self):
        return 0

    def prefill(self, slot, prompt, *, seed=0, temperature=0.0, top_k=0):
        self.pool.lengths[slot] = len(prompt)
        last = np.zeros((self.model_cfg.vocab_size,), np.float32)
        return (prompt[-1] + 1) % self.model_cfg.vocab_size, last

    def decode(self, entries):
        out = {}
        for slot, token, _seed, _temp, _tk in entries:
            self.pool.lengths[slot] += 1
            out[slot] = (token + 1) % self.model_cfg.vocab_size
        return out


def _replica(**kw):
    eng = _FakeEngine(**kw)
    batcher = ContinuousBatcher(eng).start()
    frontend = ServingFrontend(batcher, port=0).start()
    return eng, batcher, frontend


def _close(replicas):
    for _, batcher, frontend in replicas:
        batcher.close(drain=True)
        frontend.close()


def _cfg(**over):
    """A strict config the unit matrix can breach deterministically:
    one class, e2e ceiling 0.1s, 10% budget, fast/slow = 10s/30s."""
    kw = dict(
        objectives=(
            SLOObjective(slo="interactive", ttft_p95_s=0.1,
                         e2e_p95_s=0.1, error_budget=0.1,
                         availability=0.9),
        ),
        windows_s=(10.0, 30.0),
        burn_thresholds=(5.0, 2.0),
        pending_for_s=2.0,
        resolve_after_s=5.0,
    )
    kw.update(over)
    return SLOConfig(**kw)


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- config


class TestSLOConfig:
    def test_defaults_are_generous_and_valid(self):
        cfg = SLOConfig()
        assert cfg.objective("interactive").ttft_p95_s >= 5.0
        assert cfg.objective("batch") is not None
        assert cfg.objective("nope") is None
        assert cfg.windows_s[0] < cfg.windows_s[1]

    def test_save_load_round_trip(self, tmp_path):
        path = str(tmp_path / "slo.json")
        cfg = _cfg()
        cfg.save(path)
        with open(path) as f:
            doc = json.load(f)
        assert doc["version"] == slo.SLO_JSON_VERSION
        loaded = SLOConfig.load(path)
        assert loaded == cfg

    def test_bare_object_loads_without_wrapper(self, tmp_path):
        path = str(tmp_path / "bare.json")
        with open(path, "w") as f:
            json.dump({"objectives": [{"slo": "interactive",
                                       "e2e_p95_s": 1.0}]}, f)
        cfg = SLOConfig.load(path)
        assert cfg.objective("interactive").e2e_p95_s == 1.0

    def test_wrong_version_rejected(self, tmp_path):
        path = str(tmp_path / "v9.json")
        with open(path, "w") as f:
            json.dump({"version": 9, "config": {}}, f)
        with pytest.raises(ValueError, match="version"):
            SLOConfig.load(path)

    def test_duplicate_class_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            SLOConfig(objectives=(
                SLOObjective(slo="a"), SLOObjective(slo="a"),
            ))

    def test_unknown_keys_rejected(self):
        with pytest.raises(ValueError, match="unknown"):
            SLOObjective.from_json_dict({"slo": "x", "nope": 1})
        with pytest.raises(ValueError, match="unknown"):
            SLOConfig.from_json_dict({"bogus": 1})

    def test_bad_windows_rejected(self):
        with pytest.raises(ValueError, match="windows"):
            _cfg(windows_s=(30.0, 10.0))
        with pytest.raises(ValueError, match="budget"):
            SLOObjective(slo="x", error_budget=0.0)


# ----------------------------------------------------------- time series


class TestTimeSeriesStore:
    def test_ring_trims_to_capacity(self):
        ts = TimeSeriesStore(capacity=4)
        for i in range(6):
            ts.record("x", float(i), now=float(i))
        pts = ts.series("x")
        assert len(pts) == 4
        assert [v for _t, v in pts] == [2.0, 3.0, 4.0, 5.0]

    def test_sample_walks_counters_gauges_histograms(self):
        reg = MetricsRegistry()
        reg.counter("serving/requests_total").inc(3)
        reg.gauge("serving/queue_depth").set(7.0)
        for v in range(1, 101):
            reg.histogram("serving/ttft").record(v / 100.0)
        ts = TimeSeriesStore(reg, capacity=8)
        n = ts.sample(now=1.0)
        assert n >= 5  # counter + gauge + three percentile series
        assert ts.series("serving/requests_total") == [(1.0, 3.0)]
        assert ts.series("serving/queue_depth") == [(1.0, 7.0)]
        names = ts.names()
        for suffix in (".p50", ".p95", ".p99"):
            assert "serving/ttft" + suffix in names, names
        p95 = ts.series("serving/ttft.p95")[0][1]
        assert 0.90 <= p95 <= 1.0

    def test_sample_without_registry_is_noop(self):
        ts = TimeSeriesStore()
        assert ts.sample() == 0
        assert ts.names() == []

    def test_rollup_percentiles(self):
        ts = TimeSeriesStore(capacity=200)
        for i in range(1, 101):
            ts.record("lat", float(i), now=float(i))
        r = ts.rollup("lat")
        assert r["count"] == 100
        assert r["min"] == 1.0 and r["max"] == 100.0
        assert r["last"] == 100.0
        assert r["p50"] == 50.0
        assert r["p95"] == 95.0
        assert r["p99"] == 99.0
        assert ts.rollup("unknown")["count"] == 0

    def test_to_payload_is_json_ready(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        ts = TimeSeriesStore(reg, capacity=8)
        ts.sample(now=1.0)
        ts.sample(now=2.0)
        payload = json.loads(json.dumps(ts.to_payload()))
        assert payload["capacity"] == 8
        assert payload["samples_taken"] == 2
        assert payload["series"]["c"] == [[1.0, 1.0], [2.0, 1.0]]
        assert payload["rollups"]["c"]["count"] == 2
        assert payload["rollups"]["c"]["last"] == 1.0

    @pytest.mark.timeout(120)
    def test_concurrent_record_sample_scrape(self):
        """The lock-order tier's concurrency pin: writers (record +
        registry-fed sample) race scrapers (to_payload/rollup) with no
        exception, no deadlock, and a consistent final payload."""
        reg = MetricsRegistry()
        ts = TimeSeriesStore(reg, capacity=64)
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            while not stop.is_set():
                reg.counter("w/count").inc()
                ts.record("w/direct", float(i))
                ts.sample()
                i += 1

        def scraper():
            while not stop.is_set():
                payload = ts.to_payload(last=16)
                for pts in payload["series"].values():
                    assert all(len(p) == 2 for p in pts)
                ts.rollup("w/direct")

        threads = [threading.Thread(target=writer),
                   threading.Thread(target=writer),
                   threading.Thread(target=scraper),
                   threading.Thread(target=scraper)]

        def run(t):
            try:
                t.run_orig()
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        for t in threads:
            t.run_orig, t.run = t.run, lambda t=t: run(t)
            t.start()
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors
        payload = ts.to_payload()
        assert payload["samples_taken"] > 0
        assert len(payload["series"]["w/direct"]) <= 64


# --------------------------------------------------------------- engine


class TestAlertEngine:
    def _bad(self, eng, clock, n=20, *, trace_id=None, value=1.0):
        for _ in range(n):
            eng.observe("interactive", e2e_s=value, trace_id=trace_id,
                        now=clock.t)

    def _good(self, eng, clock, n=20):
        for _ in range(n):
            eng.observe("interactive", e2e_s=0.01, now=clock.t)

    def test_healthy_traffic_never_fires(self):
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        for _ in range(10):
            self._good(eng, clock, 5)
            clock.t += 1.0
            assert eng.evaluate() == []
        s = eng.stats()
        assert s["alerts_firing"] == 0 and s["alert_count"] == 0
        assert s["error_budget_remaining"] == 1.0

    def test_unknown_slo_class_ignored(self):
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        eng.observe("mystery", e2e_s=99.0, error=True)
        assert eng.evaluate() == []

    def test_sustained_breach_walks_pending_then_firing(self):
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        self._bad(eng, clock)
        assert eng.evaluate() == []  # ok -> pending, nothing emitted
        rules = eng.payload()["rules"]
        assert rules["e2e_interactive"]["state"] == "pending"
        clock.t += 1.0  # still inside pending_for_s=2.0
        self._bad(eng, clock, 5)
        assert eng.evaluate() == []
        clock.t += 1.5  # dwell satisfied
        self._bad(eng, clock, 5)
        fired = eng.evaluate()
        assert any(
            a["name"] == "e2e_interactive" and a["state"] == "firing"
            for a in fired
        )
        s = eng.stats()
        assert s["alerts_firing"] >= 1 and s["alert_count"] >= 1
        assert s["error_budget_remaining"] == 0.0

    def test_brief_flap_is_suppressed(self):
        """A breach shorter than pending_for_s never fires."""
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        self._bad(eng, clock, 3)
        assert eng.evaluate() == []  # pending
        # Health returns before the dwell elapses: back to ok.
        clock.t += 1.0
        self._good(eng, clock, 60)
        assert eng.evaluate() == []
        assert eng.payload()["rules"]["e2e_interactive"]["state"] == "ok"
        clock.t += 5.0
        assert eng.evaluate() == []
        assert eng.stats()["alert_count"] == 0

    def test_slow_window_gates_a_single_spike(self):
        """The multi-window method's reason to exist: a short spike
        saturates the fast window but not the slow one — no alert."""
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        self._good(eng, clock, 95)  # a healthy half-minute of history
        clock.t += 25.0  # good events now outside the fast window
        self._bad(eng, clock, 3)  # the spike
        assert eng.evaluate() == []
        rules = eng.payload()["rules"]["e2e_interactive"]
        assert rules["burn_rate_fast"] >= 5.0  # fast window IS burning
        assert rules["burn_rate_slow"] < 2.0  # slow window absorbs it
        assert rules["state"] == "ok"

    def test_firing_resolves_after_sustained_health(self, tmp_path):
        path = str(tmp_path / "alerts.jsonl")
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock, path=path)
        self._bad(eng, clock, 20, trace_id="t-worst")
        eng.evaluate()
        clock.t += 2.5
        self._bad(eng, clock, 5, trace_id="t-worst")
        fired = eng.evaluate()
        assert [a["state"] for a in fired] == ["firing"]
        # Health returns; bad events age past the slow window.
        clock.t += 61.0
        self._good(eng, clock, 10)
        assert eng.evaluate() == []  # healthy_since starts
        clock.t += 6.0  # > resolve_after_s
        self._good(eng, clock, 5)
        resolved = eng.evaluate()
        assert [a["state"] for a in resolved] == ["resolved"]
        assert eng.stats()["alerts_firing"] == 0
        # The sink round-trip: one line per transition, all valid v14.
        with open(path) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        assert [
            (ln["alert"]["name"], ln["alert"]["state"]) for ln in lines
        ] == [("e2e_interactive", "firing"),
              ("e2e_interactive", "resolved")]
        for ln in lines:
            assert ln["schema_version"] == 14
            assert schema.validate_line(ln) == [], ln
        alerts = slo.read_alerts(path)
        assert len(alerts) == 2
        assert alerts[0]["trace_id"] == "t-worst"
        eng.close()

    def test_read_alerts_tolerates_torn_tail(self, tmp_path):
        path = str(tmp_path / "alerts.jsonl")
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock, path=path)
        self._bad(eng, clock)
        eng.evaluate()
        clock.t += 2.5
        self._bad(eng, clock, 5)
        eng.evaluate()
        eng.close()
        with open(path, "a") as f:
            f.write('{"kind": "alert", "alert": {"name"')  # the tear
        alerts = slo.read_alerts(path)
        assert len(alerts) == 1 and alerts[0]["state"] == "firing"
        assert slo.read_alerts(str(tmp_path / "missing.jsonl")) == []

    def test_worst_offender_exemplar_wins(self):
        """The firing alert embeds the trace_id of the WORST bad event
        in the window, not the first or last."""
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        self._bad(eng, clock, 10, trace_id="t-mild", value=0.5)
        self._bad(eng, clock, 1, trace_id="t-worst", value=9.0)
        self._bad(eng, clock, 10, trace_id="t-mild2", value=0.5)
        eng.evaluate()
        clock.t += 2.5
        self._bad(eng, clock, 2, trace_id="t-mild3", value=0.5)
        fired = [a for a in eng.evaluate()
                 if a["name"] == "e2e_interactive"]
        assert fired and fired[0]["trace_id"] == "t-worst"
        assert fired[0]["value"] == 9.0
        assert fired[0]["slo"] == "interactive"

    def test_severity_page_vs_ticket(self):
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        # All-bad: burn = 1/0.1 = 10 = 2x the fast threshold -> page.
        self._bad(eng, clock, 20)
        eng.evaluate()
        clock.t += 2.5
        self._bad(eng, clock, 2)
        fired = [a for a in eng.evaluate()
                 if a["name"] == "e2e_interactive"]
        assert fired[0]["severity"] == "page"
        # 60% bad: burn 6 — over the threshold but under 2x -> ticket.
        eng2 = AlertEngine(_cfg(), registry=MetricsRegistry(),
                           now=clock)
        self._bad(eng2, clock, 12)
        self._good(eng2, clock, 8)
        eng2.evaluate()
        clock.t += 2.5
        self._bad(eng2, clock, 3)
        self._good(eng2, clock, 2)
        fired = [a for a in eng2.evaluate()
                 if a["name"] == "e2e_interactive"]
        assert fired and fired[0]["severity"] == "ticket"

    def test_probe_failures_burn_availability(self):
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        for _ in range(3):
            eng.observe_probe(slo="interactive", ok=True,
                              replica="r0", ttft_s=0.01)
        eng.observe_probe(slo="interactive", ok=False, replica="r1")
        s = eng.stats()
        assert s["probe_success_rate"] == 0.75
        # budget 1-availability = 0.1; 25% bad -> burn 2.5 < fast 5.
        assert eng.evaluate() == []
        for _ in range(10):
            eng.observe_probe(slo="interactive", ok=False,
                              replica="r1")
        eng.evaluate()
        clock.t += 2.5
        eng.observe_probe(slo="interactive", ok=False, replica="r1")
        fired = [a for a in eng.evaluate()
                 if a["name"] == "probe_interactive"]
        assert fired and fired[0]["state"] == "firing"
        assert fired[0]["replica"] == "r1"
        assert eng.stats()["probe_success_rate"] < 0.5

    def test_stats_keys_are_exactly_the_v14_serving_keys(self):
        eng = AlertEngine(registry=MetricsRegistry())
        assert set(eng.stats()) == set(schema.SERVING_KEYS_V14)

    def test_payload_shape(self):
        clock = _Clock()
        eng = AlertEngine(_cfg(), registry=MetricsRegistry(),
                          now=clock)
        payload = json.loads(json.dumps(eng.payload()))
        assert payload["firing"] == []
        assert set(payload["rules"]) == {
            "ttft_interactive", "e2e_interactive",
            "errors_interactive", "probe_interactive",
        }
        assert payload["config"]["windows_s"] == [10.0, 30.0]
        for key in schema.SERVING_KEYS_V14:
            assert key in payload


# -------------------------------------------------------------- prober


class TestCanaryProber:
    def _prober(self, replies, **kw):
        """A prober whose transport is a scripted list of (status,
        reply) tuples (popped per probe) — no sockets."""
        from tensorflow_examples_tpu.serving import prober as pmod

        p = CanaryProber({"r0": "http://fake:1"},
                         registry=MetricsRegistry(), **kw)
        calls = []

        def fake_post(url, body, timeout):
            calls.append((url, body))
            return replies.pop(0)

        return p, calls, fake_post

    def test_probe_body_carries_the_tag(self):
        p = CanaryProber({"r0": "http://fake:1"},
                         registry=MetricsRegistry())
        body = p.probe_body()
        assert body["probe"] is True
        assert body["temperature"] == 0.0
        assert body["max_new_tokens"] > 0

    def test_known_answer_banks_then_catches_mismatch(self, monkeypatch):
        from tensorflow_examples_tpu.serving import prober as pmod

        replies = [
            (200, {"tokens": [3, 4, 5], "ttft_s": 0.01}),
            (200, {"tokens": [3, 4, 5], "ttft_s": 0.01}),
            (200, {"tokens": [3, 4, 6], "ttft_s": 0.01}),  # corrupted
        ]
        p, calls, fake_post = self._prober(replies)
        monkeypatch.setattr(pmod, "post_json", fake_post)
        r1 = p.probe_one("r0", "http://fake:1")
        assert r1["ok"] is True and r1["mismatch"] is False
        r2 = p.probe_one("r0", "http://fake:1")
        assert r2["ok"] is True
        r3 = p.probe_one("r0", "http://fake:1")
        # A 200 with the wrong tokens is a FAILED probe.
        assert r3["ok"] is False and r3["mismatch"] is True
        counters = p.registry.counter_values()
        assert counters["probe/sent_total"] == 3
        assert counters["probe/mismatch_total"] == 1
        assert counters["probe/failed_total"] == 1
        assert calls[0][1]["probe"] is True

    def test_transport_failure_feeds_engine_and_fires(self, monkeypatch):
        from tensorflow_examples_tpu.serving import prober as pmod

        clock = _Clock()
        eng = AlertEngine(
            _cfg(pending_for_s=0.0), registry=MetricsRegistry(),
            now=clock,
        )
        replies = [(0, {})] * 40
        p, _calls, fake_post = self._prober(replies, alerts=eng)
        monkeypatch.setattr(pmod, "post_json", fake_post)
        p.probe_once()  # sweep + evaluate: ok -> pending
        clock.t += 0.5
        p.probe_once()  # pending dwell (0) satisfied -> firing
        assert p.advisory() is True
        assert eng.stats()["alerts_firing"] >= 1
        assert eng.stats()["probe_success_rate"] == 0.0
        assert p.registry.counter_values()["probe/failed_total"] == 2

    def test_fleet_targets_shape(self):
        targets = fleet_targets(
            "http://127.0.0.1:9000",
            ["http://a:1/", "http://b:2"],
        )
        assert targets == {
            "router": "http://127.0.0.1:9000",
            "http://a:1": "http://a:1/",
            "http://b:2": "http://b:2",
        }
        assert fleet_targets(None, ["http://a:1"]) == {
            "http://a:1": "http://a:1"
        }
        with pytest.raises(ValueError):
            CanaryProber({})

    @pytest.mark.timeout(120)
    def test_probes_real_replica_end_to_end(self):
        """One real sweep: fake engine behind a real HTTP frontend;
        the probe rides the ordinary /generate path and the replica
        tolerates (ignores) the tag."""
        replicas = [_replica()]
        url = f"http://127.0.0.1:{replicas[0][2].port}"
        try:
            p = CanaryProber({"rep": url}, registry=MetricsRegistry(),
                             timeout_s=30.0)
            first = p.probe_once()
            second = p.probe_once()
        finally:
            _close(replicas)
        assert [r["ok"] for r in first + second] == [True, True]
        assert second[0]["mismatch"] is False  # deterministic answer
        assert p.registry.counter_values()["probe/sent_total"] == 2


# ----------------------------------------------------- router exclusion


class TestProbeExclusion:
    """The exclusion contract, pinned on a real router: synthetic
    probes never enter the journal dedupe window, the tenant intent
    log, ``router/requests_total``, or the organic AlertEngine feed."""

    @pytest.mark.timeout(120)
    def test_probe_tag_excluded_from_journal_and_counters(
        self, tmp_path
    ):
        from tensorflow_examples_tpu.serving.journal import (
            RequestJournal,
        )

        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        journal = RequestJournal(str(tmp_path / "journal.jsonl"))
        router = Router(urls, journal=journal)
        router.probe_once()
        try:
            # One ORGANIC request establishes the baseline.
            status, _ = router.handle(
                {"prompt": [2], "max_new_tokens": 2,
                 "request_id": "org-1"},
                kind="generate",
            )
            assert status == 200
            base = journal.stats()
            assert base["appends"] >= 1
            assert journal.lookup("org-1") is not None
            organic_events = len(
                router.alerts._rules["errors_interactive"].events
            )
            assert organic_events == 1
            # Probe traffic: same request_id on purpose — probes must
            # not dedupe, journal, or feed the organic engine.
            body = {"prompt": [2], "max_new_tokens": 2,
                    "request_id": "probe-1", "probe": True}
            for _ in range(3):
                status, reply = router.handle(dict(body),
                                              kind="generate")
                assert status == 200 and reply["tokens"]
            assert journal.stats() == base
            assert journal.lookup("probe-1") is None
            counters = router.registry.counter_values()
            assert counters["router/requests_total"] == 1
            assert counters["probe/router_requests_total"] == 3
            assert len(
                router.alerts._rules["errors_interactive"].events
            ) == organic_events
        finally:
            router.close()
            journal.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_probe_tag_does_not_mutate_caller_body(self):
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        router = Router(urls)
        router.probe_once()
        body = {"prompt": [2], "max_new_tokens": 2, "probe": True}
        try:
            status, _ = router.handle(body, kind="generate")
            assert status == 200
            assert body["probe"] is True  # the copy was popped, not us
        finally:
            router.close()
            _close(replicas)


# --------------------------------------------------- router stats + HTTP


class TestRouterSurfaces:
    @pytest.mark.timeout(120)
    def test_stats_line_carries_v14_keys_and_validates(self):
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        router = Router(urls)
        router.probe_once()
        try:
            status, _ = router.handle(
                {"prompt": [2], "max_new_tokens": 2}, kind="generate"
            )
            assert status == 200
            line = json.loads(json.dumps(router.stats_line()))
            assert schema.validate_line(line) == []
            serving = line["serving"]
            for key in schema.SERVING_KEYS_V14:
                assert key in serving, key
            assert serving["alerts_firing"] == 0
            assert serving["alert_count"] == 0
            assert serving["error_budget_remaining"] == 1.0
            assert serving["probe_success_rate"] == 1.0
            # v14 keys on an older version label must flag.
            v13 = dict(line, schema_version=13)
            assert any(
                "v14 serving key" in p
                for p in schema.validate_line(v13)
            )
            # The stats tick also sampled the time-series ring.
            assert router.series.samples_taken == 1
            assert "router/requests_total" in router.series.names()
        finally:
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_frontends_serve_alerts_and_series(self):
        import urllib.request

        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        router = Router(urls)
        router.probe_once()
        rfront = RouterFrontend(router, port=0).start()

        def get(url):
            with urllib.request.urlopen(url, timeout=30) as resp:
                return resp.status, json.loads(resp.read())

        try:
            router.stats_line()  # one tick feeds the ring
            status, alerts = get(rfront.url("/alerts"))
            assert status == 200
            assert alerts["alerts_firing"] == 0
            assert "rules" in alerts and "config" in alerts
            status, series = get(rfront.url("/series"))
            assert status == 200
            assert series["samples_taken"] >= 1
            assert "router/replicas_eligible" in series["series"]
            # The REPLICA frontend serves /series too (fed by the
            # serve.py stats loop; here we tick it by hand).
            replicas[0][2].series.sample()
            rurl = f"http://127.0.0.1:{replicas[0][2].port}"
            status, rseries = get(rurl + "/series")
            assert status == 200
            assert rseries["samples_taken"] >= 1
        finally:
            rfront.close()
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_autoscaler_treats_firing_alert_as_advisory_hot(self):
        """The PR-12 hook: a firing alert marks the fleet hot (scale
        up) and blocks scale-down idleness, via any object with the
        AlertEngine stats() shape."""
        from tensorflow_examples_tpu.serving.supervisor import (
            Autoscaler,
            AutoscalerConfig,
        )

        class _Alerts:
            def __init__(self):
                self.firing = 0

            def stats(self):
                return {"alerts_firing": self.firing,
                        "error_budget_remaining": 1.0,
                        "probe_success_rate": 1.0, "alert_count": 0}

        class _Supervisor:
            handles = []

            def busy(self):
                return False

        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        router = Router(urls)
        router.probe_once()
        alerts = _Alerts()
        scaler = Autoscaler(
            router, _Supervisor(), lambda idx: None, alerts=alerts,
            cfg=AutoscalerConfig(min_replicas=1, max_replicas=1),
        )
        try:
            sig = scaler.fleet_signals()
            assert sig["alerts_firing"] == 0
            alerts.firing = 1
            sig = scaler.fleet_signals()
            assert sig["alerts_firing"] == 1
            # max_replicas=1 means the hot verdict cannot act — the pin
            # is the advisory counter, not the scale action.
            decision = scaler.evaluate_once()
            assert isinstance(decision, str)
            counters = router.registry.counter_values()
            assert counters.get(
                "autoscaler/alert_advisory_total", 0
            ) >= 1
        finally:
            scaler.close()
            router.close()
            _close(replicas)


# ------------------------------------------------------- schema ritual


class TestSchemaV14Ritual:
    """The versioning ritual for v14: the additions exist, and both
    the alert kind and the serving summary keys are forbidden on every
    line that predates them."""

    def test_v14_pins(self):
        assert schema.SERVING_SCHEMA_VERSION == 14
        assert schema.SERVING_KEYS_V14 == (
            "alerts_firing", "error_budget_remaining",
            "probe_success_rate", "alert_count",
        )
        assert schema.KINDS == schema.KINDS_V13 + ("alert",)
        assert schema.ALERT_STATES == ("firing", "resolved")
        assert "alert/" in schema.INSTRUMENT_PREFIXES
        assert "probe/" in schema.INSTRUMENT_PREFIXES

    def _alert_line(self, **over):
        line = {
            "schema_version": 14, "kind": "alert", "step": 0,
            "time_unix": 2.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {}, "counters": {}, "gauges": {}, "derived": {},
            "alert": {
                "name": "e2e_interactive", "slo": "interactive",
                "state": "firing", "severity": "page",
                "burn_rate": 12.5, "budget_remaining": 0.1,
                "since_unix": 1.5, "window_s": 60.0,
                "value": 2.5, "threshold": 0.5,
                "trace_id": "t" * 16, "replica": "http://a:1",
            },
        }
        line.update(over)
        return line

    def test_valid_alert_line_passes(self):
        assert schema.validate_line(self._alert_line()) == []

    def test_alert_kind_forbidden_before_v14(self):
        for version in (4, 5, 6, 7, 8, 9, 10, 11, 12, 13):
            problems = schema.validate_line(
                self._alert_line(schema_version=version))
            assert any("kind 'alert'" in p for p in problems), (
                version, problems)

    def test_v14_serving_keys_forbidden_before_v14(self):
        base = {
            "schema_version": 14, "kind": "serving", "step": 1,
            "time_unix": 1.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {}, "counters": {}, "gauges": {}, "derived": {},
            "serving": {
                "active_requests": 0, "queue_depth": 0, "slots": 4,
                "kv_occupancy": 0.0, "post_warmup_recompiles": 0,
                "draining": 0, "alerts_firing": 0,
                "error_budget_remaining": 1.0,
                "probe_success_rate": 1.0, "alert_count": 0,
            },
        }
        assert schema.validate_line(base) == []
        for version in (4, 5, 6, 7, 8, 9, 10, 11, 12, 13):
            stale = dict(base, schema_version=version)
            problems = schema.validate_line(stale)
            for key in schema.SERVING_KEYS_V14:
                assert any(
                    f"v14 serving key '{key}'" in p for p in problems
                ), (version, key, problems)

    def test_alert_object_forbidden_on_non_alert_lines(self):
        line = self._alert_line(kind="window")
        line["metrics"] = {"loss": 1.0}
        problems = schema.validate_line(line)
        assert any("alert object on a non-alert line" in p
                   for p in problems)

    def test_missing_alert_object_flagged(self):
        line = self._alert_line()
        del line["alert"]
        problems = schema.validate_line(line)
        assert any("missing the alert object" in p for p in problems)

    def test_alert_field_types_enforced(self):
        line = self._alert_line()
        line["alert"]["state"] = "screaming"
        problems = schema.validate_line(line)
        assert any("alert['state']" in p for p in problems)
        line = self._alert_line()
        line["alert"]["burn_rate"] = "hot"
        problems = schema.validate_line(line)
        assert any("'burn_rate'" in p for p in problems)
        line = self._alert_line()
        del line["alert"]["name"]
        problems = schema.validate_line(line)
        assert any("missing required key 'name'" in p for p in problems)
        line = self._alert_line()
        line["alert"]["trace_id"] = 7
        problems = schema.validate_line(line)
        assert any("'trace_id'" in p for p in problems)

    def test_v1_line_rejects_v14_field(self):
        line = {
            "schema_version": 1, "kind": "window", "step": 1,
            "time_unix": 1.0, "session_start_unix": 1.0, "host": 0,
            "metrics": {"loss": 1.0}, "counters": {}, "gauges": {},
            "derived": {}, "alert": {"name": "x"},
        }
        problems = schema.validate_line(line)
        assert any("v14 field 'alert'" in p for p in problems)
