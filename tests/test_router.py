"""Router tier (ISSUE 8): load-aware dispatch, drain-aware rollout,
retry-once-on-503, canary compare via tools/run_diff.py.

The load-bearing test is
:class:`TestDrainMidLoad::test_drain_one_replica_zero_failed_requests`
— the acceptance contract: 2 replicas under concurrent load, one
drained mid-stream, every request completes 200 and the drained
replica takes no new dispatch.

Replicas here are device-free fake engines behind REAL HTTP frontends:
the router only ever speaks HTTP, so this is end-to-end for everything
the router tier owns while staying O(ms) per request. The real-engine
tier (2 warmed paged replicas behind the router over HTTP) is covered
by ``serve_bench --smoke --router`` in tests/test_tools.py.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from tensorflow_examples_tpu.serving import kv_cache
from tensorflow_examples_tpu.serving.batcher import (
    ContinuousBatcher,
    Request,
)
from tensorflow_examples_tpu.serving.engine import ServeConfig
from tensorflow_examples_tpu.serving.frontend import ServingFrontend
from tensorflow_examples_tpu.serving.router import (
    ReplicaState,
    Router,
    RouterConfig,
    RouterFrontend,
)
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


class _FakeEngine:
    """Deterministic device-free engine (mirrors test_serving's): token
    stream is prompt[-1]+1, +2, ... — so any replica serves identical
    output and the router's routing cannot change results."""

    def __init__(self, *, max_slots=4, max_queue=32, max_len=64,
                 step_delay=0.0):
        self.cfg = ServeConfig(
            max_slots=max_slots, max_queue=max_queue, max_delay_s=0.0,
            request_timeout_s=30.0,
        )
        import serve_bench

        from tensorflow_examples_tpu.models import transformer

        base = dict(serve_bench.SMOKE_MODEL)
        base["max_len"] = max_len
        self.model_cfg = transformer.TransformerConfig(**base)
        self.registry = MetricsRegistry()
        self.pool = kv_cache.KVCachePool(
            num_layers=1, num_slots=max_slots, num_heads=1,
            max_len=max_len, head_dim=2, registry=self.registry,
        )
        self.step_delay = step_delay
        self.warmed = True

    def post_warmup_recompiles(self):
        return 0

    def prefill(self, slot, prompt, *, seed=0, temperature=0.0, top_k=0):
        self.pool.lengths[slot] = len(prompt)
        last = np.zeros((self.model_cfg.vocab_size,), np.float32)
        return (prompt[-1] + 1) % self.model_cfg.vocab_size, last

    def decode(self, entries):
        if self.step_delay:
            time.sleep(self.step_delay)
        out = {}
        for slot, token, _seed, _temp, _tk in entries:
            self.pool.lengths[slot] += 1
            out[slot] = (token + 1) % self.model_cfg.vocab_size
        return out


def _replica(**kw):
    eng = _FakeEngine(**kw)
    batcher = ContinuousBatcher(eng).start()
    frontend = ServingFrontend(batcher, port=0).start()
    return eng, batcher, frontend


def _close(replicas):
    for _, batcher, frontend in replicas:
        batcher.close(drain=True)
        frontend.close()


def _post(url, body, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class TestPick:
    """Dispatch policy units — no sockets, states set by hand."""

    def _router(self):
        r = Router(["http://a:1", "http://b:2"])
        for rep in r.replicas:
            rep.probed = True
        return r

    def test_least_loaded_by_queue_then_occupancy(self):
        r = self._router()
        a, b = r.replicas
        a.queue_depth, b.queue_depth = 3.0, 0.0
        assert r.pick() is b
        a.queue_depth = b.queue_depth = 0.0
        a.kv_occupancy, b.kv_occupancy = 0.9, 0.1
        assert r.pick() is b

    def test_tie_breaks_to_fewest_dispatched(self):
        r = self._router()
        a, b = r.replicas
        picked = {r.pick().url for _ in range(2)}
        assert picked == {a.url, b.url}  # alternates on the tiebreak

    def test_drained_and_unhealthy_excluded(self):
        r = self._router()
        a, b = r.replicas
        a.drained = True
        assert r.pick() is b
        b.failures = r.cfg.unhealthy_after
        assert r.pick() is None
        assert r.undrain(a.url) and r.pick() is a

    def test_remote_draining_excluded(self):
        r = self._router()
        a, b = r.replicas
        a.draining_remote = True
        for _ in range(3):
            assert r.pick() is b

    def test_replica_state_snapshot_shape(self):
        s = ReplicaState("http://x:9/").snapshot_locked()
        assert s["url"] == "http://x:9" and s["set"] == "base"


class TestRouterE2E:
    @pytest.mark.timeout(120)
    def test_dispatch_spreads_and_proxies(self):
        replicas = [_replica(), _replica()]
        urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe in replicas]
        router = Router(
            urls, cfg=RouterConfig(probe_interval_s=0.05)
        ).start()
        rfront = RouterFrontend(router, port=0).start()
        try:
            for i in range(8):
                status, reply = _post(
                    rfront.url("/generate"),
                    {"prompt": [10 + i], "max_new_tokens": 3},
                )
                assert status == 200
                assert reply["tokens"] == [
                    (10 + i + k + 1) % 211 for k in range(3)
                ]
            # Both replicas took work (least-loaded ties alternate).
            assert all(r.dispatched > 0 for r in router.replicas)
            # Observability surface.
            line = router.stats_line()
            assert schema.validate_line(json.loads(json.dumps(line))) == []
            assert line["serving"]["replicas"] == 2
            assert line["serving"]["router_dispatched"] == 8
            with urllib.request.urlopen(
                rfront.url("/replicas"), timeout=10
            ) as resp:
                snap = json.loads(resp.read())
            assert len(snap["replicas"]) == 2
            with urllib.request.urlopen(
                rfront.url("/health"), timeout=10
            ) as resp:
                health = json.loads(resp.read())
            assert health["ok"] and health["eligible"] == 2
        finally:
            rfront.close()
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_retry_once_on_503_lands_on_other_replica(self):
        """Replica A is draining (its frontend answers 503) but the
        router has not probed since: the dispatch hits A, gets the
        503, and retries ONCE onto B — the client sees 200."""
        replicas = [_replica(), _replica()]
        urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe in replicas]
        # No probe thread (start() not called): the router's view is
        # frozen at one manual sweep, so it provably dispatches to the
        # already-draining replica first.
        router = Router(urls, cfg=RouterConfig())
        router.probe_once()
        try:
            a, b = router.replicas
            replicas[0][1].close(drain=True)  # A drains itself
            # Force the first pick onto A (fewest dispatched).
            b.dispatched = 5
            status, reply = router.handle(
                {"prompt": [7], "max_new_tokens": 2}, kind="generate"
            )
            assert status == 200 and reply["tokens"] == [8, 9]
            assert a.errors == 1
            counters = router.registry.counter_values()
            assert counters["router/retries_total"] == 1
        finally:
            router.close()
            _close(replicas[1:])
            replicas[0][2].close()

    @pytest.mark.timeout(120)
    def test_no_replica_is_503_not_hang(self):
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe in replicas]
        router = Router(
            urls, cfg=RouterConfig(probe_interval_s=60.0)
        ).start()
        try:
            router.drain(urls[0])
            status, reply = router.handle(
                {"prompt": [1]}, kind="generate"
            )
            assert status == 503 and reply.get("retry")
            assert (
                router.registry.counter_values()[
                    "router/no_replica_total"
                ] == 1
            )
        finally:
            router.close()
            _close(replicas)


class TestDrainMidLoad:
    @pytest.mark.timeout(180)
    def test_drain_one_replica_zero_failed_requests(self):
        """Acceptance: 2 replicas, concurrent load, one drained via the
        admin endpoint mid-stream -> every request completes, zero
        failures, and the drained replica takes no dispatch after the
        drain settles."""
        replicas = [
            _replica(step_delay=0.01), _replica(step_delay=0.01)
        ]
        urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe in replicas]
        router = Router(
            urls, cfg=RouterConfig(probe_interval_s=0.05)
        ).start()
        rfront = RouterFrontend(router, port=0).start()
        n, statuses = 24, [None] * 24
        drained_at_dispatch: list[int] = []
        next_i = [0]
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    i = next_i[0]
                    if i >= n:
                        return
                    next_i[0] += 1
                if i == 8:
                    # Mid-load rollout drain via the admin verb.
                    status, reply = _post(
                        rfront.url("/drain"), {"replica": urls[0]}
                    )
                    assert status == 200 and reply["ok"]
                    drained_at_dispatch.append(
                        router.replicas[0].dispatched
                    )
                s, _ = _post(
                    rfront.url("/generate"),
                    {"prompt": [i % 200], "max_new_tokens": 4},
                )
                statuses[i] = s

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(4)
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert statuses.count(200) == n, statuses  # ZERO failures
            # Post-drain, replica 0 took at most the requests already
            # being picked concurrently with the drain call.
            assert router.replicas[0].dispatched <= (
                drained_at_dispatch[0] + 4
            )
            # ...and the survivor carried the rest.
            assert router.replicas[1].dispatched >= n // 2
        finally:
            rfront.close()
            router.close()
            _close(replicas)


class TestCanary:
    @pytest.mark.timeout(120)
    def test_canary_split_and_run_diff_record(self, tmp_path):
        """Acceptance: canary compare produces a run_diff doc — two
        per-set records through tools/run_diff.py with the serving
        keys ranked."""
        import run_diff

        replicas = [_replica(), _replica(step_delay=0.01)]
        urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe in replicas]
        router = Router(
            [urls[0]], canary=[urls[1]],
            cfg=RouterConfig(
                probe_interval_s=0.05, canary_fraction=0.5
            ),
        ).start()
        rfront = RouterFrontend(router, port=0).start()
        try:
            for i in range(10):
                status, _ = _post(
                    rfront.url("/generate"),
                    {"prompt": [i + 1], "max_new_tokens": 3},
                )
                assert status == 200
            base, canary = router.canary_records()
            assert base["completed"] == 5 and canary["completed"] == 5
            assert base["set"] == "base" and canary["set"] == "canary"
            # /canary serves the same records.
            with urllib.request.urlopen(
                rfront.url("/canary"), timeout=10
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["base"]["completed"] == 5
        finally:
            rfront.close()
            router.close()
            _close(replicas)
        a_path, b_path = tmp_path / "base.json", tmp_path / "canary.json"
        a_path.write_text(json.dumps(base))
        b_path.write_text(json.dumps(canary))
        out = tmp_path / "diff.json"
        rc = run_diff.main([str(a_path), str(b_path), "--json", str(out)])
        assert rc == 0
        with open(out) as f:
            diff = json.load(f)
        ranked = {d["metric"] for d in diff["ranked"]}
        assert "ttft_p95_ms" in ranked and "tok_per_s" in ranked
        # The canary's gateable serving figures are flattened on top
        # (bench_gate --record consumes this doc directly).
        assert diff["ttft_p95_ms"] == canary["ttft_p95_ms"]


class TestCircuitBreaker:
    """ISSUE 10 breaker state machine: closed -> open (ejected) ->
    half-open (one trial) -> closed on success / re-open on failure.
    Driven through the router's own bookkeeping, no sockets."""

    def _router(self, **cfg_kw):
        kw = dict(eject_after=3, eject_cooldown_s=0.2)
        kw.update(cfg_kw)
        r = Router(["http://a:1", "http://b:2"], cfg=RouterConfig(**kw))
        for rep in r.replicas:
            rep.probed = True
        return r

    def test_consecutive_failures_eject(self):
        r = self._router()
        a = r.replicas[0]
        for i in range(r.cfg.eject_after - 1):
            r._note_failure(a, transport=True, draining=False)
            assert a.breaker == "closed", i
        r._note_failure(a, transport=True, draining=False)
        assert a.breaker == "open"
        assert not a.eligible(r.cfg.unhealthy_after)
        assert (
            r.registry.counter_values()["router/ejections_total"] == 1
        )

    def test_success_resets_consecutive_count(self):
        r = self._router()
        a = r.replicas[0]
        for _ in range(r.cfg.eject_after - 1):
            r._note_failure(a, transport=False, draining=False)
        r._note_success(a)
        assert a.consec_errors == 0
        r._note_failure(a, transport=False, draining=False)
        assert a.breaker == "closed"  # the streak was broken

    def test_draining_503_is_not_a_breaker_failure(self):
        r = self._router(eject_after=1)
        a = r.replicas[0]
        r._note_failure(a, transport=False, draining=True)
        assert a.breaker == "closed" and a.draining_remote

    def test_half_open_single_trial_then_readmit(self):
        r = self._router(eject_after=1)
        a, b = r.replicas
        b.drained = True  # force every pick onto a
        r._note_failure(a, transport=True, draining=False)
        assert a.breaker == "open"
        assert r.pick() is None  # ejected: nothing eligible
        time.sleep(r.cfg.eject_cooldown_s + 0.05)
        trial = r.pick()  # cooldown expired -> half-open, ONE trial
        assert trial is a and a.breaker == "half_open"
        assert r.pick() is None  # trial in flight: no second dispatch
        r._note_success(a)
        assert a.breaker == "closed"
        assert (
            r.registry.counter_values()["router/readmits_total"] == 1
        )
        assert r.pick() is a  # back in rotation

    def test_half_open_failure_reopens(self):
        r = self._router(eject_after=1)
        a, b = r.replicas
        b.drained = True
        r._note_failure(a, transport=True, draining=False)
        time.sleep(r.cfg.eject_cooldown_s + 0.05)
        assert r.pick() is a and a.breaker == "half_open"
        r._note_failure(a, transport=True, draining=False)
        assert a.breaker == "open"  # re-ejected for another cooldown
        assert r.pick() is None
        assert (
            r.registry.counter_values()["router/ejections_total"] == 2
        )

    def test_probe_green_readmits_half_open(self):
        """The /health-probe path of the half-open trial: a green probe
        readmits without risking a live request."""
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe in replicas]
        router = Router(
            urls, cfg=RouterConfig(eject_after=1, eject_cooldown_s=0.05)
        )
        try:
            router.probe_once()
            a = router.replicas[0]
            router._note_failure(a, transport=False, draining=False)
            assert a.breaker == "open"
            time.sleep(0.1)
            router.probe_once()
            assert a.breaker == "closed"
            assert (
                router.registry.counter_values()[
                    "router/readmits_total"
                ] == 1
            )
        finally:
            router.close()
            _close(replicas)


class TestBoundedRetryAndFailover:
    @pytest.mark.timeout(120)
    def test_transport_failure_fails_over_and_counts(self):
        """A replica that died mid-request (transport failure, status
        0) triggers in-flight failover: the request replays on the
        other replica and router/failovers_total counts it."""
        replicas = [_replica()]
        live_url = f"http://127.0.0.1:{replicas[0][2].port}"
        # A dead URL: bind-then-close guarantees connection refused.
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_url = f"http://127.0.0.1:{s.getsockname()[1]}"
        router = Router(
            [dead_url, live_url],
            cfg=RouterConfig(retry_backoff_s=0.01, eject_after=1),
        )
        router.probe_once()
        try:
            # Force the first pick onto the dead replica.
            router.replicas[1].dispatched = 5
            status, reply = router.handle(
                {"prompt": [7], "max_new_tokens": 2}, kind="generate"
            )
            assert status == 200 and reply["tokens"] == [8, 9]
            counters = router.registry.counter_values()
            assert counters["router/failovers_total"] == 1
            assert counters["router/retries_total"] == 1
            assert counters["router/ejections_total"] == 1
            assert router.replicas[0].breaker == "open"
        finally:
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_retries_bounded_by_max_retries(self):
        """Every replica down -> the request fails 503 after at most
        max_retries re-dispatches, never an unbounded loop."""
        import socket

        urls = []
        for _ in range(2):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                urls.append(f"http://127.0.0.1:{s.getsockname()[1]}")
        router = Router(
            urls,
            cfg=RouterConfig(
                max_retries=2, retry_backoff_s=0.01,
                retry_budget_s=5.0, eject_after=10,
            ),
        )
        try:
            status, reply = router.handle(
                {"prompt": [1]}, kind="generate"
            )
            assert status == 503
            counters = router.registry.counter_values()
            assert counters["router/retries_total"] == 2
        finally:
            router.close()


class TestHedgedDispatch:
    @pytest.mark.timeout(120)
    def test_hedge_wins_and_loser_is_discarded(self):
        """A slow primary past the hedge deadline triggers a second
        dispatch; the fast hedge's response wins, the slow loser is
        abandoned (counted, its reply discarded on arrival)."""
        slow = _replica(step_delay=0.25)
        fast = _replica()
        urls = [
            f"http://127.0.0.1:{slow[2].port}",
            f"http://127.0.0.1:{fast[2].port}",
        ]
        router = Router(
            urls, cfg=RouterConfig(hedge_after_s=0.05)
        )
        router.probe_once()
        try:
            # Force the primary pick onto the slow replica.
            router.replicas[1].dispatched = 5
            status, reply = router.handle(
                {"prompt": [7], "max_new_tokens": 4}, kind="generate"
            )
            assert status == 200
            # Determinism across replicas: same tokens either way.
            assert reply["tokens"] == [8, 9, 10, 11]
            counters = router.registry.counter_values()
            assert counters["router/hedges_total"] == 1
            assert counters["router/hedge_wins_total"] == 1
            assert counters["router/hedge_cancelled_total"] == 1
            # The winner was the fast replica; the slow loser's reply
            # lands later and is discarded (bookkeeping only).
            assert router.replicas[1].completed == 1
        finally:
            router.close()
            _close([slow, fast])

    def test_hedge_disabled_by_default(self):
        assert RouterConfig().hedge_after_s == 0.0


class TestProbeGarbage:
    """ISSUE 10 satellite: malformed /health bodies mark the replica
    unhealthy instead of risking the probe loop."""

    def _garbage_server(self, payload: bytes):
        import http.server
        import threading

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(
            target=httpd.serve_forever, daemon=True
        ).start()
        return httpd

    @pytest.mark.timeout(120)
    @pytest.mark.parametrize(
        "payload", [b"<<<not json", b"[1, 2, 3]", b'"just a string"'],
        ids=["non-json", "json-array", "json-string"],
    )
    def test_garbage_health_body_marks_unhealthy(self, payload):
        garbage = self._garbage_server(payload)
        replicas = [_replica()]
        urls = [
            f"http://127.0.0.1:{garbage.server_address[1]}",
            f"http://127.0.0.1:{replicas[0][2].port}",
        ]
        router = Router(urls, cfg=RouterConfig())
        try:
            for _ in range(router.cfg.unhealthy_after):
                router.probe_once()  # must never raise
            bad, good = router.replicas
            assert bad.failures >= router.cfg.unhealthy_after
            assert not bad.eligible(router.cfg.unhealthy_after)
            # The sweep survived the garbage and still probed the
            # well-behaved replica.
            assert good.probed and good.failures == 0
            status, _ = router.handle(
                {"prompt": [5], "max_new_tokens": 2}, kind="generate"
            )
            assert status == 200
        finally:
            router.close()
            _close(replicas)
            garbage.shutdown()
            garbage.server_close()


class TestRouterSchema:
    def test_v6_serving_keys_flagged_on_older_versions(self):
        r = Router(["http://a:1"])
        line = json.loads(json.dumps(r.stats_line()))
        assert schema.validate_line(line) == []
        v5 = dict(line, schema_version=5)
        assert any(
            "v6 serving key" in p for p in schema.validate_line(v5)
        )
        v4 = dict(line, schema_version=4)
        assert any(
            "v6 serving key" in p for p in schema.validate_line(v4)
        )

    def test_v7_serving_keys_flagged_on_older_versions(self):
        """ISSUE 10: the fault-tolerance counters are v7-only — a 'v6'
        line carrying router_failovers is a mislabeled v7 line."""
        r = Router(["http://a:1"])
        line = json.loads(json.dumps(r.stats_line()))
        assert line["schema_version"] == schema.SERVING_SCHEMA_VERSION
        assert schema.validate_line(line) == []
        for key in schema.SERVING_KEYS_V7:
            assert key in line["serving"], key
        v6 = dict(line, schema_version=6)
        assert any(
            "v7 serving key" in p for p in schema.validate_line(v6)
        )

    def test_v9_serving_keys_flagged_on_older_versions(self):
        """ISSUE 12: the router's fleet-summed prefix summary is
        v9-only — a 'v8' line carrying prefix_blocks is a mislabeled
        v9 line, same rule as every earlier bump."""
        r = Router(["http://a:1"])
        rep = r.replicas[0]
        rep.probed = True
        rep.prefix_blocks, rep.prefix_chains = 5, 2
        line = json.loads(json.dumps(r.stats_line()))
        assert schema.validate_line(line) == []
        assert line["serving"]["prefix_blocks"] == 5
        assert line["serving"]["prefix_chains"] == 2
        v8 = dict(line, schema_version=8)
        assert any(
            "v9 serving key" in p for p in schema.validate_line(v8)
        )


class TestRouterAffinityProbe:
    @pytest.mark.timeout(120)
    def test_probe_learns_role_and_digest_fields(self):
        """The probe sweep parses the ISSUE 12 /health fields even from
        a dense-pool replica (role only) and the /replicas snapshot
        carries them."""
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe in replicas]
        router = Router(urls, cfg=RouterConfig(probe_interval_s=60.0))
        try:
            router.probe_once()
            rep = router.replicas[0]
            assert rep.role == "mixed"  # ServeConfig default
            assert rep.prefix_digest == frozenset()
            snap = rep.snapshot_locked()
            assert snap["role"] == "mixed"
            assert snap["prefix_blocks"] == 0
        finally:
            router.close()
            _close(replicas)


class TestFleetLockDiscipline:
    """Regression tests for the ISSUE 14 graftlint lock-pass findings:
    ``drain``/``undrain`` mutated ``ReplicaState.drained``/``failures``
    WITHOUT the router lock (while ``quarantine``/``readmit`` and
    ``pick()`` took it — a drain racing a pick could dispatch to a
    just-drained replica), and ``health_payload``/``stats_line``
    aggregated the fleet view with no lock at all, so a probe sweep
    mid-render could tear it (one replica's fresh occupancy summed
    with another's stale brownout level). Both now serialize on
    ``Router._lock`` — pinned here by holding the lock from another
    thread and asserting the verb blocks until release."""

    def _assert_serializes(self, router, call):
        locked = threading.Event()
        release = threading.Event()
        holder_done = threading.Event()

        def hold():
            with router._lock:
                locked.set()
                release.wait(5)
            holder_done.set()

        done = threading.Event()

        def run():
            call()
            done.set()

        t1 = threading.Thread(target=hold, daemon=True)
        t1.start()
        assert locked.wait(2)
        t2 = threading.Thread(target=run, daemon=True)
        t2.start()
        # The verb must be waiting on the fleet lock, not mutating
        # lock-free past it (the pre-fix behavior).
        time.sleep(0.1)
        assert not done.is_set(), (
            f"{call.__name__} completed while Router._lock was held — "
            "it is not serializing with pick()/the probe sweep"
        )
        release.set()
        assert done.wait(2), f"{call.__name__} never finished post-release"
        t1.join(2)
        t2.join(2)

    def test_drain_takes_the_fleet_lock(self):
        router = Router(["http://127.0.0.1:9/"])
        self._assert_serializes(
            router, lambda: router.drain("http://127.0.0.1:9/")
        )
        assert router.replicas[0].drained

    def test_undrain_takes_the_fleet_lock(self):
        router = Router(["http://127.0.0.1:9/"])
        router.drain("http://127.0.0.1:9/")
        self._assert_serializes(
            router, lambda: router.undrain("http://127.0.0.1:9/")
        )
        assert not router.replicas[0].drained

    def test_fleet_views_take_the_fleet_lock(self):
        router = Router(["http://127.0.0.1:9/"])
        self._assert_serializes(router, lambda: router.health_payload())
        self._assert_serializes(router, lambda: router.stats_line())
        self._assert_serializes(
            router, lambda: router.replica_snapshots()
        )

    def test_drained_replica_never_picked_after_drain_returns(self):
        """Functional shape of the race: once drain() returns, no
        concurrent pick() may return the drained replica — hammered
        from several threads while the drain flips."""
        urls = ["http://127.0.0.1:9/", "http://127.0.0.1:10/"]
        router = Router(urls)
        for r in router.replicas:
            r.probed = True
        stop = threading.Event()
        drained_at = []
        bad = []

        def picker():
            while not stop.is_set():
                t_start = time.monotonic()
                r = router.pick()
                # Only a pick that STARTED after drain() returned is a
                # violation — the lock serializes it behind the drain,
                # so it must see drained=True.
                if (
                    r is not None and drained_at
                    and t_start > drained_at[0]
                    and r.url == urls[0].rstrip("/")
                ):
                    bad.append(r.url)

        threads = [
            threading.Thread(target=picker, daemon=True)
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)
        router.drain(urls[0])
        drained_at.append(time.monotonic())
        time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(2)
        assert not bad, f"picked drained replica after drain(): {bad}"


class TestTracing:
    """ISSUE 18: per-request trace trees over fake replicas — the wire
    contract (reply ``trace_id``, ``GET /trace/{id}``), per-attempt
    dispatch spans under failover, client context adoption, the v13
    stats keys, /metrics exemplars, and the journal dedupe stitch."""

    @pytest.mark.timeout(120)
    def test_reply_trace_id_and_trace_endpoint(self):
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        router = Router(urls, cfg=RouterConfig(probe_interval_s=0.05))
        router.probe_once()
        rfront = RouterFrontend(router, port=0).start()
        try:
            status, reply = _post(
                rfront.url("/generate"),
                {"prompt": [7], "max_new_tokens": 3},
            )
            assert status == 200 and reply["tokens"] == [8, 9, 10]
            tid = reply["trace_id"]
            assert isinstance(tid, str) and tid
            with urllib.request.urlopen(
                rfront.url(f"/trace/{tid}"), timeout=10
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["trace_id"] == tid
            names = [s["name"] for s in doc["spans"]]
            # Router-side spans plus the replica's own, stitched via
            # the reply's trace_spans — one tree, no shared memory.
            assert "request" in names and "dispatch" in names
            assert "queue_wait" in names, names
            # The replica spans nest under the dispatch attempt.
            by_id = {s["span_id"]: s for s in doc["spans"]}
            disp = next(s for s in doc["spans"] if s["name"] == "dispatch")
            qw = next(s for s in doc["spans"] if s["name"] == "queue_wait")
            assert qw["parent_id"] == disp["span_id"]
            assert by_id[disp["parent_id"]]["name"] == "request"
            # Unknown id -> 404, not a crash.
            try:
                with urllib.request.urlopen(
                    rfront.url("/trace/nope"), timeout=10
                ) as resp:
                    assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
                assert "unknown trace" in json.loads(e.read())["error"]
        finally:
            rfront.close()
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_failover_trace_shows_both_dispatch_attempts(self):
        """A transport-failure failover leaves BOTH attempts in the
        tree: the dead replica's dispatch span (outcome=transport) and
        the survivor's (outcome=ok), each with its own span_id — plus
        the failover/retried flags that force the tail sampler to
        keep the trace."""
        replicas = [_replica()]
        live_url = f"http://127.0.0.1:{replicas[0][2].port}"
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead_url = f"http://127.0.0.1:{s.getsockname()[1]}"
        router = Router(
            [dead_url, live_url],
            cfg=RouterConfig(retry_backoff_s=0.01, eject_after=1),
        )
        router.probe_once()
        try:
            router.replicas[1].dispatched = 5  # force the dead pick
            status, reply = router.handle(
                {"prompt": [7], "max_new_tokens": 2}, kind="generate"
            )
            assert status == 200 and reply["tokens"] == [8, 9]
            doc = router.recorder.get(reply["trace_id"])
            assert doc is not None and not doc.get("open")
            assert "failover" in doc["flags"]
            assert "retried" in doc["flags"]
            assert doc["kept"] is True  # forced keep, not seeded luck
            dispatches = [
                s for s in doc["spans"] if s["name"] == "dispatch"
            ]
            assert len(dispatches) == 2
            outcomes = {
                s["tags"]["replica"]: s["tags"]["outcome"]
                for s in dispatches
            }
            assert outcomes[dead_url] == "transport"
            assert outcomes[live_url] == "ok"
            assert (
                dispatches[0]["span_id"] != dispatches[1]["span_id"]
            )
            # Replica spans hang off the attempt that answered, never
            # the dead one.
            qw = [s for s in doc["spans"] if s["name"] == "queue_wait"]
            live_span = next(
                s for s in dispatches if s["tags"]["replica"] == live_url
            )
            assert qw and all(
                s["parent_id"] == live_span["span_id"] for s in qw
            )
        finally:
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_client_wire_context_is_adopted(self):
        """A client-minted traceparent wins: the reply carries the
        client's trace_id and the root request span parents under the
        client's span — the client can stitch the router's tree into
        its own."""
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        router = Router(urls)
        router.probe_once()
        try:
            status, reply = router.handle(
                {
                    "prompt": [3], "max_new_tokens": 2,
                    "trace": {
                        "trace_id": "cafe" * 4,
                        "parent_span_id": "feed0123",
                        "sampled": True,
                    },
                },
                kind="generate",
            )
            assert status == 200
            assert reply["trace_id"] == "cafe" * 4
            doc = router.recorder.get("cafe" * 4)
            root = next(
                s for s in doc["spans"] if s["name"] == "request"
            )
            assert root["parent_id"] == "feed0123"
        finally:
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_stats_line_carries_v13_keys_and_validates(self):
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        # sample_fraction=1.0: this test is about the keys, not the
        # sampler's coin.
        router = Router(
            urls, cfg=RouterConfig(trace_sample_fraction=1.0)
        )
        router.probe_once()
        try:
            status, _ = router.handle(
                {"prompt": [2], "max_new_tokens": 2}, kind="generate"
            )
            assert status == 200
            line = json.loads(json.dumps(router.stats_line()))
            assert schema.validate_line(line) == []
            serving = line["serving"]
            for key in schema.SERVING_KEYS_V13:
                assert key in serving, key
            assert serving["traces_kept"] == 1
            assert serving["traces_dropped"] == 0
            assert serving["trace_coverage"] == 1.0
            # v13 keys on an older version label must flag.
            v12 = dict(line, schema_version=12)
            assert any(
                "v13 serving key" in p for p in schema.validate_line(v12)
            )
        finally:
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_metrics_exposes_e2e_exemplar_with_trace_id(self):
        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        router = Router(urls)
        router.probe_once()
        rfront = RouterFrontend(router, port=0).start()
        try:
            status, reply = _post(
                rfront.url("/generate"),
                {"prompt": [5], "max_new_tokens": 2},
            )
            assert status == 200
            with urllib.request.urlopen(
                rfront.url("/metrics"), timeout=10
            ) as resp:
                text = resp.read().decode()
            line = next(
                ln for ln in text.splitlines()
                if ln.startswith("router_e2e_seconds_worst{")
            )
            # The exemplar names the trace that explains the worst
            # observation — here the only one there is.
            assert f'trace_id="{reply["trace_id"]}"' in line
        finally:
            rfront.close()
            router.close()
            _close(replicas)

    @pytest.mark.timeout(120)
    def test_journal_dedupe_stitches_into_original_trace(self, tmp_path):
        """A duplicated request_id answers from the journal — and its
        spans JOIN the original trace (journal-stamped trace_id +
        recorder merge), instead of forking a second tree."""
        from tensorflow_examples_tpu.serving.journal import (
            RequestJournal,
        )

        replicas = [_replica()]
        urls = [f"http://127.0.0.1:{replicas[0][2].port}"]
        journal = RequestJournal(str(tmp_path / "j.jsonl"))
        router = Router(urls, journal=journal)
        router.probe_once()
        try:
            body = {
                "prompt": [9], "max_new_tokens": 2,
                "request_id": "rid-1",
            }
            status, first = router.handle(body, kind="generate")
            assert status == 200 and not first.get("dedup")
            tid = first["trace_id"]
            assert journal.lookup("rid-1")["trace_id"] == tid
            status, second = router.handle(body, kind="generate")
            assert status == 200 and second["dedup"] is True
            assert second["tokens"] == first["tokens"]
            # The stitch: the duplicate's reply names the ORIGINAL
            # trace, and the merged doc holds both passes' spans.
            assert second["trace_id"] == tid
            doc = router.recorder.get(tid)
            names = [s["name"] for s in doc["spans"]]
            assert "dispatch" in names  # original pass
            assert "dedupe_hit" in names  # duplicate's fast path
            assert names.count("request") == 2  # one root per pass
            assert "deduped" in doc["flags"]
            assert doc["kept"] is True
        finally:
            router.close()
            _close(replicas)
