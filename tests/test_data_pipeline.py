"""ISSUE 6: host input pipeline — sharded parallel readers, background
decode/augment workers, the parallel ImageNet stream's determinism
contract (bit-identical to the sequential reference for any reader/
worker count, per-host sharding, torn-tail/resume), the data_wait vs
data_work span split, depth-adaptive prefetch, and the batched augment
helpers."""

import io
import os
import threading
import time

import numpy as np
import pytest

from tensorflow_examples_tpu.data import augment as augment_mod
from tensorflow_examples_tpu.data import imagenet as imagenet_data
from tensorflow_examples_tpu.data import prefetch as prefetch_mod
from tensorflow_examples_tpu.data import sources as sources_mod
from tensorflow_examples_tpu.data import workers as workers_mod
from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import spans as spans_mod


@pytest.fixture
def fresh_registry():
    reg = registry_mod.reset_default_registry()
    tracer = spans_mod.reset_default_tracer()
    yield reg, tracer
    registry_mod.reset_default_registry()
    spans_mod.reset_default_tracer()


def _take(it, n):
    out = [next(it) for _ in range(n)]
    close = getattr(it, "close", None)
    if close is not None:
        close()
    return out


# ------------------------------------------------------ TFRecord (pure)


class TestPureTFRecord:
    def test_roundtrip_and_tf_interop(self, tmp_path):
        path = str(tmp_path / "train-00000-of-00001")
        recs = [
            sources_mod.make_example(
                {"image/encoded": bytes([i]) * 5, "image/class/label": i + 1}
            )
            for i in range(7)
        ]
        assert sources_mod.write_tfrecord(path, recs) == 7
        back = list(sources_mod.iter_tfrecord_records(path, verify_crc=True))
        assert back == recs
        parsed = sources_mod.parse_example(back[3])
        assert parsed["image/encoded"] == [bytes([3]) * 5]
        assert parsed["image/class/label"] == [4]
        tf = pytest.importorskip("tensorflow")
        # tf's reader verifies our CRCs; tf's parser reads our proto.
        got = [
            int(
                tf.io.parse_single_example(
                    r,
                    {"image/class/label": tf.io.FixedLenFeature([], tf.int64)},
                )["image/class/label"]
            )
            for r in tf.data.TFRecordDataset([path])
        ]
        assert got == list(range(1, 8))
        # and our parser reads tf-written examples
        ex = tf.train.Example(
            features=tf.train.Features(
                feature={
                    "f": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[5, -3])
                    )
                }
            )
        ).SerializeToString()
        assert sources_mod.parse_example(ex)["f"] == [5, -3]

    def test_truncated_record_is_loud(self, tmp_path):
        """A record cut off mid-frame raises (tf DataLossError parity):
        silent truncation would desync the cached record count the
        resume arithmetic trusts. EOF on a record boundary is clean."""
        path = str(tmp_path / "train-torn")
        recs = [b"record-%d" % i for i in range(5)]
        sources_mod.write_tfrecord(path, recs)
        assert list(sources_mod.iter_tfrecord_records(path)) == recs
        size = os.path.getsize(path)
        with open(path, "r+b") as f:  # tear the last record mid-payload
            f.truncate(size - 7)
        it = sources_mod.iter_tfrecord_records(path)
        assert [next(it) for _ in range(4)] == recs[:4]
        with pytest.raises(ValueError, match="truncated record"):
            next(it)

    def test_seeded_window_shuffle_mixes_and_replays(self):
        items = list(range(200))
        rng = lambda: np.random.default_rng(11)  # noqa: E731
        a = list(sources_mod.seeded_window_shuffle(iter(items), 32, rng()))
        b = list(sources_mod.seeded_window_shuffle(iter(items), 32, rng()))
        assert a == b  # pure function of (stream, rng)
        assert sorted(a) == items  # a permutation: no dupes, no drops
        assert a != items  # actually shuffles
        c = list(
            sources_mod.seeded_window_shuffle(
                iter(items), 32, np.random.default_rng(12)
            )
        )
        assert c != a  # seed-dependent
        assert list(
            sources_mod.seeded_window_shuffle(iter(items), 1, rng())
        ) == items  # window<=1 is a pass-through


# ------------------------------------------------------- sharded reader


class TestShardedReader:
    def _shards(self, n_shards=6, seed=0):
        rng = np.random.default_rng(seed)
        return [
            [f"s{s}r{r}" for r in range(int(rng.integers(2, 9)))]
            for s in range(n_shards)
        ]

    def test_merge_identical_for_any_reader_count(self):
        shards = self._shards()
        ref = [r for shard in shards for r in shard]
        for n in (1, 2, 3, 8):
            got = list(
                sources_mod.interleave_shards(shards, iter, num_readers=n)
            )
            assert got == ref, f"num_readers={n} broke the merge order"

    def test_per_host_union_exactly_once(self):
        shards = self._shards(n_shards=7, seed=3)
        ref = [r for shard in shards for r in shard]
        for hosts in (2, 3):
            union = []
            for h in range(hosts):
                union.extend(
                    sources_mod.interleave_shards(
                        shards[h::hosts], iter, num_readers=2
                    )
                )
            assert sorted(union) == sorted(ref)  # no dupes, no drops

    def test_reader_error_raised_in_stream_order(self):
        def read_fn(shard):
            if shard == "bad":
                raise OSError("disk ate it")
            return iter([shard])

        it = sources_mod.interleave_shards(
            ["a", "bad", "c"], read_fn, num_readers=2
        )
        assert next(it) == "a"
        with pytest.raises(RuntimeError, match="bad"):
            list(it)

    def test_global_lookahead_bounded(self):
        """Many small shards + a stalled consumer: readers stop at the
        max_ahead window instead of buffering the whole split."""
        reads = []

        def read_fn(shard):
            reads.append(shard)
            return iter([shard])

        reader = sources_mod.ShardedReader(
            list(range(50)), read_fn, num_readers=4,
            buffer_records=8, block_records=1, max_ahead=4,
        )
        try:
            stream = reader.records()
            assert next(stream) == 0
            time.sleep(0.25)  # consumer stalled mid-shard
            assert len(reads) <= 4 + 1, reads  # the window, not the list
        finally:
            reader.close()

    def test_close_stops_reader_threads(self):
        """Readers blocked on FULL shard buffers (the abandoned-consumer
        case) must exit promptly on close — no orphan threads."""
        started = threading.active_count()

        def read_fn(shard):
            for r in range(100_000):
                yield (shard, r)

        reader = sources_mod.ShardedReader(
            list(range(4)), read_fn, num_readers=3,
            buffer_records=4, block_records=1,
        )
        stream = reader.records()
        assert next(stream) == (0, 0)
        reader.close()
        deadline = time.time() + 5
        while threading.active_count() > started and time.time() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= started


# --------------------------------------------------------- worker pool


class TestWorkerPool:
    def test_ordered_results_match_inline_map(self):
        rng = np.random.default_rng(0)
        delays = rng.uniform(0, 0.003, size=40)

        def fn(i):
            time.sleep(delays[i])
            return i * i

        with workers_mod.WorkerPool(fn, 4) as pool:
            got = list(pool.map_ordered(range(40)))
        assert got == [i * i for i in range(40)]

    def test_exception_surfaces_at_its_position(self):
        def fn(i):
            if i == 5:
                raise ValueError("item five")
            return i

        pool = workers_mod.WorkerPool(fn, 3)
        try:
            it = pool.map_ordered(range(10))
            assert [next(it) for _ in range(5)] == list(range(5))
            with pytest.raises(workers_mod.WorkerError, match="item 5"):
                next(it)
        finally:
            pool.close()

    def test_poison_pill_shutdown_no_orphans(self):
        import sys

        started = threading.active_count()
        interval0 = sys.getswitchinterval()
        pool = workers_mod.WorkerPool(lambda x: x, 4)
        assert sys.getswitchinterval() <= 0.001  # pipeline handoff mode
        assert threading.active_count() == started + 4
        it = pool.map_ordered(range(100))
        assert next(it) == 0
        pool.close()
        pool.close()  # idempotent
        deadline = time.time() + 5
        while threading.active_count() > started and time.time() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= started
        # the GIL switch interval is restored once no pool is live
        assert sys.getswitchinterval() == pytest.approx(interval0)
        with pytest.raises(RuntimeError, match="closed"):
            pool.submit(0, 1)

    def test_in_flight_bounded_by_depth(self):
        seen = []

        def fn(i):
            seen.append(i)
            return i

        pool = workers_mod.WorkerPool(fn, 2, depth=3)
        try:
            it = pool.map_ordered(range(50))
            next(it)
            time.sleep(0.1)  # stalled consumer: pool must not run ahead
            assert len(seen) <= 1 + 3 + pool.num_workers
        finally:
            pool.close()

    def test_workers_record_data_work_spans(self, fresh_registry):
        reg, _ = fresh_registry
        with workers_mod.WorkerPool(lambda x: x, 2, registry=reg) as pool:
            list(pool.map_ordered(range(8)))
        (p95,) = reg.histogram("span/data_work").percentiles(95)
        assert p95 is not None
        assert reg.counter("data/worker_items").value == 8

    def test_close_flips_closed_under_the_condition(self):
        """Regression for the ISSUE 14 graftlint lock-pass finding:
        ``close()`` set ``_closed`` OUTSIDE ``self._cond`` and only
        notified after joining every worker — a ``result()`` waiter
        discovered the shutdown on its next 0.1s poll tick (or up to
        ``num_workers * join_timeout`` later), not when it happened.
        The flag now flips and notifies under the condition: pinned by
        holding the condition from another thread and asserting
        close() blocks until release."""
        pool = workers_mod.WorkerPool(lambda x: x, 1)
        acquired = threading.Event()
        release = threading.Event()

        def hold():
            with pool._cond:
                acquired.set()
                release.wait(5)

        closed = threading.Event()

        def close():
            pool.close()
            closed.set()

        t1 = threading.Thread(target=hold, daemon=True)
        t1.start()
        assert acquired.wait(2)
        t2 = threading.Thread(target=close, daemon=True)
        t2.start()
        time.sleep(0.1)
        assert not closed.is_set(), (
            "close() ran past the condition while a waiter held it — "
            "the closed flag is not condition-guarded"
        )
        release.set()
        assert closed.wait(5)
        t1.join(2)
        t2.join(2)

    def test_close_wakes_blocked_result_waiter(self):
        """A result() caller blocked on a seq that will never arrive
        must be released by close() with the closed-pool RuntimeError
        (not strand until some later poll/join)."""
        pool = workers_mod.WorkerPool(lambda x: x, 1)
        outcome = []

        def wait_forever():
            try:
                pool.result(999)  # never submitted
            except RuntimeError as e:
                outcome.append(e)

        t = threading.Thread(target=wait_forever, daemon=True)
        t.start()
        time.sleep(0.05)  # let it enter the cond wait
        pool.close()
        t.join(3)
        assert not t.is_alive(), "result() waiter never released"
        assert outcome and "closed" in str(outcome[0])


# ------------------------------------------- parallel ImageNet pipeline


def _jpeg(rng, h=40, w=48):
    from PIL import Image

    img = rng.integers(0, 255, (h, w, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=85)
    return buf.getvalue()


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    """4 train shards / 16 records (labels = 1-based record index, so a
    decoded label IS the record's global index) + a 6-record validation
    shard. Sized so a 2-host split at batch 4 has no epoch remainder."""
    root = tmp_path_factory.mktemp("imagenet_shards")
    rng = np.random.default_rng(0)
    idx = 0
    for s in range(4):
        recs = []
        for _ in range(4):
            idx += 1
            recs.append(
                sources_mod.make_example(
                    {"image/encoded": _jpeg(rng), "image/class/label": idx}
                )
            )
        sources_mod.write_tfrecord(
            str(root / f"train-{s:05d}-of-00004"), recs
        )
    recs = [
        sources_mod.make_example(
            {"image/encoded": _jpeg(rng), "image/class/label": 1 + (i % 4)}
        )
        for i in range(6)
    ]
    sources_mod.write_tfrecord(str(root / "validation-00000-of-00001"), recs)
    cache = tmp_path_factory.mktemp("cache")
    old = os.environ.get("TFE_TPU_CACHE_DIR")
    os.environ["TFE_TPU_CACHE_DIR"] = str(cache)
    yield str(root)
    if old is None:
        os.environ.pop("TFE_TPU_CACHE_DIR", None)
    else:
        os.environ["TFE_TPU_CACHE_DIR"] = old


def _train_iter(root, **kw):
    base = dict(
        train=True, image_size=32, seed=5, host_index=0, host_count=1
    )
    base.update(kw)
    return imagenet_data.parallel_tfrecord_iter(root, "train", 4, **base)


class TestParallelImagenet:
    def test_parallel_bit_identical_to_sequential(self, shard_dir):
        # 16 records / batch 4 -> bpe 4; 10 batches cross 2+ epoch
        # boundaries (reshuffled shard order each epoch).
        ref = _take(_train_iter(shard_dir, num_readers=1, num_workers=0), 10)
        for readers, nw in ((2, 2), (3, 4)):
            got = _take(
                _train_iter(
                    shard_dir, num_readers=readers, num_workers=nw
                ),
                10,
            )
            for want, have in zip(ref, got):
                np.testing.assert_array_equal(want["label"], have["label"])
                np.testing.assert_array_equal(want["image"], have["image"])

    def test_resume_replays_exactly(self, shard_dir):
        full = _take(_train_iter(shard_dir, num_readers=2, num_workers=2), 9)
        # mid-epoch, at the epoch boundary (bpe=4), and past it
        for start in (2, 4, 5):
            got = _take(
                _train_iter(
                    shard_dir,
                    num_readers=2,
                    num_workers=2,
                    start_step=start,
                ),
                3,
            )
            for want, have in zip(full[start:], got):
                np.testing.assert_array_equal(want["label"], have["label"])
                np.testing.assert_array_equal(want["image"], have["image"])

    def test_epochs_reshuffle_records_within_shards(self, shard_dir):
        """The record-level shuffle window: consecutive epochs must not
        replay identical batch sequences (the tf.data path's 16*batch
        shuffle-buffer semantics, seeded per epoch)."""
        it = _train_iter(shard_dir, num_readers=2, num_workers=0)
        epoch0 = [tuple(int(x) for x in b["label"]) for b in _take(it, 4)]
        it = _train_iter(
            shard_dir, num_readers=2, num_workers=0, start_step=4
        )
        epoch1 = [tuple(int(x) for x in b["label"]) for b in _take(it, 4)]
        assert sorted(sum(epoch0, ())) == sorted(sum(epoch1, ()))  # same set
        assert epoch0 != epoch1  # different order

    def test_two_host_union_is_the_full_epoch_exactly_once(self, shard_dir):
        # Each host holds 2 shards / 8 records -> bpe 2 at batch 4, no
        # remainder: one epoch across hosts must cover every record
        # exactly once (labels are unique record indices).
        labels = []
        for host in range(2):
            for b in _take(
                _train_iter(
                    shard_dir,
                    num_readers=2,
                    num_workers=2,
                    host_index=host,
                    host_count=2,
                ),
                2,
            ):
                labels.extend(int(x) for x in b["label"])
        assert sorted(labels) == list(range(16))

    def test_fallback_decode_identical_too(self, shard_dir, monkeypatch):
        monkeypatch.setenv("TFE_TPU_NATIVE_DECODE", "0")
        ref = _take(_train_iter(shard_dir, num_readers=1, num_workers=0), 4)
        got = _take(_train_iter(shard_dir, num_readers=2, num_workers=3), 4)
        for want, have in zip(ref, got):
            np.testing.assert_array_equal(want["image"], have["image"])

    def test_eval_pads_final_batch_with_mask(self, shard_dir):
        batches = list(
            imagenet_data.parallel_tfrecord_iter(
                shard_dir, "validation", 4, train=False, image_size=32,
                num_readers=2, num_workers=2, host_index=0, host_count=1,
            )
        )
        assert len(batches) == 2
        assert batches[0]["mask"].sum() == 4
        assert batches[1]["mask"].sum() == 2
        assert batches[1]["image"].shape == (4, 32, 32, 3)

    def test_abandoned_pipeline_leaves_no_threads(self, shard_dir):
        started = threading.active_count()
        it = _train_iter(shard_dir, num_readers=2, num_workers=3)
        next(it)
        it.close()
        deadline = time.time() + 5
        while threading.active_count() > started and time.time() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= started


# ------------------------------------- prefetch: span split + depth


class TestPrefetchSplit:
    def _sharding(self):
        import jax

        return jax.sharding.SingleDeviceSharding(jax.devices()[0])

    def _batches(self, n):
        for i in range(n):
            yield {"x": np.full((2,), i, np.float32)}

    def test_sync_iterator_is_data_work(self, fresh_registry):
        reg, _ = fresh_registry
        out = list(
            prefetch_mod.device_prefetch(self._batches(5), self._sharding())
        )
        assert len(out) == 5
        assert reg.histogram("span/data_work").percentiles(95)[0] is not None
        assert reg.histogram("span/data_wait").percentiles(95)[0] is None

    def test_background_iterator_is_data_wait_and_closed(
        self, fresh_registry
    ):
        reg, _ = fresh_registry
        outer = self

        class BG:
            background = True
            closed = False

            def __init__(self):
                self._it = outer._batches(4)

            def __iter__(self):
                return self

            def __next__(self):
                return next(self._it)

            def close(self):
                self.closed = True

        bg = BG()
        out = list(prefetch_mod.device_prefetch(bg, self._sharding()))
        assert len(out) == 4 and bg.closed
        assert reg.histogram("span/data_wait").percentiles(95)[0] is not None

    def test_lookahead_bounded_by_depth(self, fresh_registry):
        pulled = []

        def src():
            for i in range(20):
                pulled.append(i)
                yield {"x": np.zeros((1,), np.float32)}

        it = prefetch_mod.device_prefetch(
            src(), self._sharding(), depth=3
        )
        next(it)
        # 3 primed + 1 refill after the pop; never the whole stream
        assert len(pulled) <= 4

    def test_depth_controller_grows_then_shrinks(self):
        reg = registry_mod.MetricsRegistry()
        ctl = prefetch_mod.DepthController(
            2, 6, registry=reg, adapt_every=2
        )
        for _ in range(8):
            reg.histogram("span/data_fetch").record(0.1)
            reg.histogram("span/device_step").record(0.01)
        for _ in range(12):
            ctl.observe()
        assert ctl.depth == 6  # input-bound: grew to the bound
        reg2 = registry_mod.MetricsRegistry()
        ctl2 = prefetch_mod.DepthController(
            2, 6, registry=reg2, adapt_every=2
        )
        ctl2.depth = 5
        for _ in range(8):
            reg2.histogram("span/data_fetch").record(0.0001)
            reg2.histogram("span/device_step").record(0.05)
        for _ in range(12):
            ctl2.observe()
        assert ctl2.depth == 2  # queue ahead: decayed to the floor
        assert reg2.gauge("data/prefetch_depth").value == 2.0

    def test_fixed_depth_controller_is_inert(self):
        reg = registry_mod.MetricsRegistry()
        ctl = prefetch_mod.DepthController(2, 0, registry=reg)
        for _ in range(50):
            reg.histogram("span/data_fetch").record(1.0)
            reg.histogram("span/device_step").record(0.001)
            ctl.observe()
        assert ctl.depth == 2


# --------------------------------------------------- batched augment


class TestBatchedAugment:
    def test_uint8_lut_byte_identical_to_per_image_loop(self):
        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (6, 9, 7, 3), np.uint8)
        mean = imagenet_data.MEAN_RGB
        std = imagenet_data.STDDEV_RGB
        got = augment_mod.normalize_images(imgs, mean, std)
        per_image = np.stack(
            [(im.astype(np.float32) / 255.0 - mean) / std for im in imgs]
        )
        assert got.dtype == np.float32
        np.testing.assert_array_equal(got, per_image.astype(np.float32))

    def test_float_branch_byte_identical(self):
        rng = np.random.default_rng(2)
        imgs = rng.uniform(0, 255, (3, 5, 5, 3)).astype(np.float32)
        mean = imagenet_data.MEAN_RGB
        std = imagenet_data.STDDEV_RGB
        np.testing.assert_array_equal(
            augment_mod.normalize_images(imgs, mean, std),
            ((imgs / 255.0) - mean) / std,
        )

    def test_flip_images_matches_loop(self):
        rng = np.random.default_rng(3)
        imgs = rng.integers(0, 256, (5, 4, 6, 3), np.uint8)
        flips = np.array([1, 0, 0, 1, 1], np.uint8)
        ref = imgs.copy()
        for i, f in enumerate(flips):
            if f:
                ref[i] = ref[i, :, ::-1]
        np.testing.assert_array_equal(
            augment_mod.flip_images(imgs, flips), ref
        )

    def test_cifar_uint8_fallback_uses_batched_normalize(self, monkeypatch):
        """The uint8 fallback (native lib absent) must equal the
        per-image formula under the same seeded draws."""
        from tensorflow_examples_tpu import native
        from tensorflow_examples_tpu.data.sources import (
            CIFAR10_MEAN,
            CIFAR10_STD,
        )

        monkeypatch.setattr(
            native, "crop_flip_normalize", lambda *a, **k: None
        )
        rng = np.random.default_rng(7)
        imgs = rng.integers(0, 256, (4, 32, 32, 3), np.uint8)
        batch = {"image": imgs, "label": np.arange(4, dtype=np.int32)}
        out = augment_mod.cifar_augment(batch, np.random.default_rng(9))
        # replay the same draw order on the float path
        rng2 = np.random.default_rng(9)
        b = 4
        pad = 4
        ys = rng2.integers(0, 2 * pad + 1, size=b)
        xs = rng2.integers(0, 2 * pad + 1, size=b)
        flips = rng2.random(b) < 0.5
        crop = augment_mod._crop_flip(
            imgs.astype(np.float32) / 255.0, ys, xs, flips, pad=pad
        )
        want = ((crop - CIFAR10_MEAN) / CIFAR10_STD).astype(np.float32)
        np.testing.assert_array_equal(out["image"], want)
