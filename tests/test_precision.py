"""Precision registry (ISSUE 15): per-row int8/fp8 quantization, the
serializable PrecisionConfig rules table, load-time tree quantization,
and the sharding composition (scales placed like their weights).

The serving-side acceptance — quantized batcher golden, byte claims,
schema v11 — lives in tests/test_serving.py / test_sharding.py /
test_tools.py; this file pins the registry's own contracts.
"""

import json

import numpy as np
import pytest

from tensorflow_examples_tpu.core import precision as P

pytestmark = pytest.mark.serving


# ------------------------------------------------------ row quantization


class TestRowQuantization:
    def test_int8_roundtrip_error_bounded(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        x = rng.standard_normal((6, 64)).astype(np.float32) * 3.0
        q, s = P.quantize_rows(jnp.asarray(x), jnp.int8)
        assert q.dtype == jnp.int8 and s.shape == (6,)
        back = np.asarray(P.dequantize_rows(q, s))
        # Symmetric absmax: per-row error <= half a quantization step.
        step = np.abs(x).max(axis=-1, keepdims=True) / P.INT8_MAX
        assert np.all(np.abs(back - x) <= 0.5 * step + 1e-7)

    def test_zero_row_exact(self):
        import jax.numpy as jnp

        x = jnp.zeros((2, 8), jnp.float32)
        q, s = P.quantize_rows(x, jnp.int8)
        assert np.all(np.asarray(s) == 1.0)
        assert np.all(np.asarray(P.dequantize_rows(q, s)) == 0.0)

    def test_int8_matches_legacy_helper(self):
        """quantize_rows(int8) IS quantize_int8_rows — the paged pool's
        contract has one implementation."""
        import jax.numpy as jnp

        x = jnp.asarray(
            np.random.default_rng(1).standard_normal((3, 16)), jnp.float32
        )
        q1, s1 = P.quantize_rows(x, jnp.int8)
        q2, s2 = P.quantize_int8_rows(x)
        assert np.array_equal(np.asarray(q1), np.asarray(q2))
        assert np.array_equal(np.asarray(s1), np.asarray(s2))

    @pytest.mark.skipif(not P.fp8_supported(), reason="no fp8 backend")
    def test_fp8_roundtrip_error_bounded(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((4, 32)).astype(np.float32)
        q, s = P.quantize_rows(x, P.fp8_dtype())
        back = np.asarray(P.dequantize_rows(q, s))
        # e4m3 carries a ~2^-3 relative mantissa step per element.
        amax = np.abs(x).max(axis=-1, keepdims=True)
        assert np.all(np.abs(back - x) <= amax * 0.05 + 1e-7)

    def test_host_quantizer_matches_device(self):
        """Load-time (numpy) quantization == the jnp path bit for bit —
        the tree a sharded engine places is the tree an unsharded one
        computes."""
        import jax.numpy as jnp

        x = np.random.default_rng(3).standard_normal((5, 24)).astype(
            np.float32
        )
        qh, sh = P._quantize_rows_host(x, "int8")
        qd, sd = P.quantize_rows(jnp.asarray(x), jnp.int8)
        assert np.array_equal(qh, np.asarray(qd))
        assert np.array_equal(sh, np.asarray(sd))


# ----------------------------------------------------------- the registry


def _tree():
    rng = np.random.default_rng(7)
    return {
        "wte": {"embedding": rng.standard_normal((40, 8)).astype(
            np.float32
        )},
        "h_0": {
            "ln_1": {
                "scale": np.ones(8, np.float32),
                "bias": np.zeros(8, np.float32),
            },
            "attn": {
                "qkv": {
                    "kernel": rng.standard_normal((8, 3, 2, 4)).astype(
                        np.float32
                    ),
                    "bias": np.zeros((3, 2, 4), np.float32),
                },
            },
            "mlp_fc": {
                "kernel": rng.standard_normal((8, 32)).astype(np.float32),
                "bias": np.zeros(32, np.float32),
            },
        },
        "step": np.int32(3),  # non-floating leaves pass through
    }


class TestPrecisionConfig:
    def test_weight_only_rules_and_json_roundtrip(self, tmp_path):
        cfg = P.PrecisionConfig.weight_only("int8", kv_dtype="fp8")
        assert cfg.quantizes and cfg.kv_dtype == "fp8"
        assert cfg.dtype_for("h_0/mlp_fc/kernel") == "int8"
        assert cfg.dtype_for("wte/embedding") == "int8"
        assert cfg.dtype_for("h_0/ln_1/scale") == ""
        path = str(tmp_path / "precision.json")
        cfg.save(path)
        assert P.PrecisionConfig.load(path) == cfg
        with open(path) as f:
            assert json.load(f)["version"] == P.PRECISION_JSON_VERSION

    def test_first_match_wins(self):
        cfg = P.PrecisionConfig(
            rules=((r"mlp_fc/kernel", ""), (r"kernel", "int8")),
        )
        assert cfg.dtype_for("h_0/mlp_fc/kernel") == ""
        assert cfg.dtype_for("h_0/attn/qkv/kernel") == "int8"

    def test_validation_is_loud(self):
        with pytest.raises(ValueError, match="dtype"):
            P.PrecisionConfig(rules=(("x", "int4"),))
        with pytest.raises(ValueError, match="kv_dtype"):
            P.PrecisionConfig(kv_dtype="bf16")
        with pytest.raises(ValueError, match="unknown"):
            P.PrecisionConfig.from_json_dict({"nope": 1})
        with pytest.raises(ValueError, match="not in"):
            P.PrecisionConfig.weight_only("f16")
        # Malformed rules are ValueError (the documented contract),
        # never a TypeError out of the unpack.
        with pytest.raises(ValueError, match="rule"):
            P.PrecisionConfig(rules=(5,))
        with pytest.raises(ValueError, match="rules"):
            P.PrecisionConfig.from_json_dict({"rules": [5]})
        with pytest.raises(ValueError, match="rules"):
            P.PrecisionConfig.from_json_dict({"rules": "kernel:int8"})

    def test_empty_dtype_is_identity(self):
        cfg = P.PrecisionConfig.weight_only("")
        assert not cfg.quantizes
        tree = _tree()
        out = P.quantize_tree(tree, cfg)
        assert out["h_0"]["mlp_fc"]["kernel"] is tree["h_0"]["mlp_fc"][
            "kernel"
        ]


class TestQuantizeTree:
    def test_kernels_quantize_norms_and_ints_pass_through(self):
        tree = _tree()
        out = P.quantize_tree(tree, P.PrecisionConfig.weight_only("int8"))
        assert isinstance(out["wte"]["embedding"], P.QuantizedWeight)
        assert isinstance(
            out["h_0"]["attn"]["qkv"]["kernel"], P.QuantizedWeight
        )
        # Per-row scales drop exactly the last axis.
        qkv = out["h_0"]["attn"]["qkv"]["kernel"]
        assert qkv.scale.shape == (8, 3, 2)
        assert not isinstance(out["h_0"]["ln_1"]["scale"],
                              P.QuantizedWeight)
        assert not isinstance(out["h_0"]["mlp_fc"]["bias"],
                              P.QuantizedWeight)
        assert out["step"] == np.int32(3)

    def test_one_d_leaves_never_quantize_even_under_blanket_rule(self):
        out = P.quantize_tree(
            _tree(), P.PrecisionConfig(default="int8")
        )
        assert not isinstance(out["h_0"]["ln_1"]["bias"],
                              P.QuantizedWeight)
        assert isinstance(out["h_0"]["mlp_fc"]["kernel"],
                          P.QuantizedWeight)

    def test_tree_paths_expose_q_and_scale_leaves(self):
        """The sharding composition hinges on this: a QuantizedWeight
        flattens into q/scale leaves UNDER the weight's own path, so
        the weight's rule places both (scale by rank clipping)."""
        import jax

        out = P.quantize_tree(_tree(), P.PrecisionConfig.weight_only(
            "int8"
        ))
        paths = {
            P._tree_path_str(p)
            for p, _ in jax.tree_util.tree_flatten_with_path(out)[0]
        }
        assert "h_0/mlp_fc/kernel/q" in paths
        assert "h_0/mlp_fc/kernel/scale" in paths
        assert "wte/embedding/q" in paths

    def test_bytes_ratio_and_stats(self):
        tree = _tree()
        out = P.quantize_tree(tree, P.PrecisionConfig.weight_only("int8"))
        stats = P.tree_precision_stats(out)
        f32_stats = P.tree_precision_stats(tree)
        assert stats["weight_bits"] == 8
        assert stats["quantized_params"] == 3
        assert stats["param_bytes_f32"] == f32_stats["param_bytes"]
        assert stats["param_bytes"] < 0.5 * stats["param_bytes_f32"]
        assert f32_stats["quantized_params"] == 0
        assert f32_stats["weight_bits"] == 32

    def test_stats_agree_with_tree_bytes(self):
        """tree_precision_stats' stored-byte walk and
        telemetry/memory.tree_bytes are two sources of the same HBM
        number (the precision/param_bytes gauge vs the gated
        hbm_bytes_per_replica) — pinned equal so they cannot silently
        desynchronize."""
        from tensorflow_examples_tpu.telemetry.memory import tree_bytes

        for cfg in (P.PrecisionConfig.weight_only("int8"),
                    P.PrecisionConfig.weight_only("")):
            out = P.quantize_tree(_tree(), cfg)
            assert P.tree_precision_stats(out)["param_bytes"] == \
                tree_bytes(out)

    def test_cast_rules_cast(self):
        import jax.numpy as jnp

        out = P.quantize_tree(
            _tree(), P.PrecisionConfig(rules=((r"kernel", "bf16"),))
        )
        assert out["h_0"]["mlp_fc"]["kernel"].dtype == jnp.bfloat16

    def test_fp8_rule_without_support_is_loud(self, monkeypatch):
        monkeypatch.setattr(P, "fp8_supported", lambda: False)
        with pytest.raises(ValueError, match="fp8"):
            P.quantize_tree(
                _tree(), P.PrecisionConfig.weight_only("fp8")
            )


class TestMaterialize:
    def test_passthrough_on_plain_leaves(self):
        import jax.numpy as jnp

        x = jnp.ones((2, 3))
        assert P.materialize(x) is x
        assert np.array_equal(
            np.asarray(P.take_rows(x, jnp.asarray([1]))), np.ones((1, 3))
        )

    def test_dequant_in_jit_matches_eager(self):
        import jax
        import jax.numpy as jnp

        w = np.random.default_rng(9).standard_normal((8, 16)).astype(
            np.float32
        )
        qw = P.QuantizedWeight(*P._quantize_rows_host(w, "int8"))
        f = jax.jit(lambda t, x: jnp.dot(x, P.materialize(t)))
        x = jnp.ones((2, 8))
        assert np.allclose(
            np.asarray(f(qw, x)),
            np.asarray(x) @ np.asarray(qw.dequantize()),
        )

    def test_take_rows_gathers_then_dequantizes(self):
        import jax.numpy as jnp

        w = np.random.default_rng(11).standard_normal((12, 6)).astype(
            np.float32
        )
        qw = P.QuantizedWeight(*P._quantize_rows_host(w, "int8"))
        idx = jnp.asarray([3, 0, 7])
        got = np.asarray(P.take_rows(qw, idx))
        want = np.asarray(qw.dequantize())[np.asarray(idx)]
        assert np.array_equal(got, want)
