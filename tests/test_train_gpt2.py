"""GPT-2 workload end-to-end: tiny-config training on dp/tp/sp meshes."""

import jax
import numpy as np
import pytest

from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
from tensorflow_examples_tpu.data.memory import eval_batches, train_iterator
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import gpt2


def tiny_config(**kw):
    base = dict(
        vocab_size=64,
        seq_len=16,
        num_layers=2,
        num_heads=4,
        d_model=32,
        dropout=0.0,
        attention="xla",
        global_batch_size=16,
        train_steps=30,
        warmup_steps=5,
        learning_rate=3e-3,
        log_every=10,
        checkpoint_every=0,
        eval_every=0,
        precision="f32",
    )
    base.update(kw)
    return gpt2.Gpt2Config(**base)


def run_tiny(cfg, mesh):
    task = gpt2.make_task(cfg, mesh=mesh)
    trainer = Trainer(task, cfg, mesh=mesh)
    train_ds, _ = gpt2.datasets(cfg)
    it = train_iterator(train_ds, cfg.global_batch_size, seed=0)
    first = None
    state, metrics = trainer.state, None
    for _ in range(cfg.train_steps):
        state, metrics = trainer._train_step(state, trainer._put_batch(next(it)))
        if first is None:
            first = float(metrics["loss"])
    trainer.state = state
    return first, float(metrics["loss"]), trainer


def test_loss_decreases_dp(mesh8):
    first, last, _ = run_tiny(tiny_config(), mesh8)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.2, f"no learning: {first} -> {last}"


def test_loss_decreases_tp_sp():
    """TP over `model` + ring attention over `context`, one jitted step."""
    mesh = create_mesh(MeshConfig(data=2, model=2, context=2))
    cfg = tiny_config(attention="ring", train_steps=20)
    first, last, _ = run_tiny(cfg, mesh)
    assert last < first - 0.1, f"no learning: {first} -> {last}"


def test_tp_matches_dp_step():
    """One train step under TP must match the pure-DP step numerically."""
    cfg = tiny_config(train_steps=3)
    mesh_dp = create_mesh(MeshConfig(data=8))
    mesh_tp = create_mesh(MeshConfig(data=2, model=4))
    _, loss_dp, _ = run_tiny(cfg, mesh_dp)
    _, loss_tp, _ = run_tiny(cfg, mesh_tp)
    assert abs(loss_dp - loss_tp) < 1e-3, (loss_dp, loss_tp)


def test_eval_and_fused_ce(mesh8):
    cfg = tiny_config(train_steps=5, fused_ce=True)
    _, _, trainer = run_tiny(cfg, mesh8)
    eval_ds = gpt2.eval_dataset(cfg)
    metrics = trainer.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    assert "nll" in metrics and np.isfinite(metrics["nll"])


def test_grad_accumulation(mesh8):
    cfg = tiny_config(train_steps=8, grad_accum_steps=2)
    first, last, _ = run_tiny(cfg, mesh8)
    assert np.isfinite(last)


def test_pipeline_parallel_matches_sequential():
    """GPipe pipelined block stack == sequential application, fwd + grad."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.models import transformer
    from tensorflow_examples_tpu.parallel.pipeline import pipeline_apply

    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    mcfg = transformer.TransformerConfig(
        vocab_size=64, max_len=16, num_layers=4, num_heads=2, d_model=16,
        dropout=0.0, attention="xla",
    )
    blocks = transformer.init_stacked_blocks(mcfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16), jnp.float32)

    ref = transformer.apply_stacked_blocks(mcfg, blocks, x)
    stage_params = jax.tree.map(
        lambda p: p.reshape((4, 1) + p.shape[1:]), blocks
    )
    fn = lambda sp, h: pipeline_apply(
        lambda p, y: transformer.apply_stacked_blocks(mcfg, p, y),
        sp, h, mesh=mesh, num_microbatches=4,
    )
    out = jax.jit(fn)(stage_params, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    g_ref = jax.grad(lambda b: jnp.sum(
        transformer.apply_stacked_blocks(mcfg, b, x) ** 2))(blocks)
    g_pp = jax.jit(jax.grad(lambda sp: jnp.sum(fn(sp, x) ** 2)))(stage_params)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a).reshape(np.asarray(b).shape), np.asarray(b), atol=5e-4
        )


def test_loss_decreases_pp():
    """End-to-end GPipe training step through the shared loop."""
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    cfg = tiny_config(num_layers=4, train_steps=20, num_microbatches=4)
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.1, f"no learning: {first} -> {last}"


def test_moe_expert_parallel():
    """Switch-MoE GPT-2: aux loss present, learns, EP-sharded on mesh."""
    mesh = create_mesh(MeshConfig(data=2, model=4))
    cfg = tiny_config(moe_experts=4, train_steps=25, learning_rate=2e-3)
    task = gpt2.make_task(cfg, mesh=mesh)
    trainer = Trainer(task, cfg, mesh=mesh)
    train_ds, _ = gpt2.datasets(cfg)
    it = train_iterator(train_ds, cfg.global_batch_size, seed=0)
    losses = []
    state = trainer.state
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        losses.append(float(m["loss"]))
        assert np.isfinite(float(m["moe_aux"]))
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1
    # Expert params must actually shard over the model axis.
    w_in = state.params["h_1"]["moe"]["w_in"]
    spec = w_in.sharding.spec
    assert spec and spec[0] == "model", spec


def test_tp_vocab_matches_dense():
    """Vocab-parallel fused CE == dense head CE (same seed, 3 steps)."""
    mesh = create_mesh(MeshConfig(data=2, model=4))
    cfg_dense = tiny_config(train_steps=3)
    cfg_tp = tiny_config(train_steps=3, tp_vocab=True)
    _, loss_dense, _ = run_tiny(cfg_dense, mesh)
    _, loss_tp, _ = run_tiny(cfg_tp, mesh)
    assert abs(loss_dense - loss_tp) < 1e-3, (loss_dense, loss_tp)


def test_tp_vocab_uneven_vocab():
    """Vocab not divisible by the model axis (padding path) still works."""
    mesh = create_mesh(MeshConfig(data=2, model=4))
    cfg = tiny_config(train_steps=4, tp_vocab=True, vocab_size=67)
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)


def test_checkpoint_restores_across_mesh_layouts(tmp_path):
    """A checkpoint saved under pure-DP restores into a TP-sharded state:
    orbax re-lays arrays out to the live mesh (checkpoint.py claim)."""
    from tensorflow_examples_tpu.train.checkpoint import CheckpointManager

    cfg = tiny_config(train_steps=3)
    mesh_dp = create_mesh(MeshConfig(data=8))
    _, _, trainer_dp = run_tiny(cfg, mesh_dp)
    ckpt = CheckpointManager(str(tmp_path), async_save=False)
    ckpt.save(3, trainer_dp.state)
    ckpt.close()

    mesh_tp = create_mesh(MeshConfig(data=2, model=4))
    task_tp = gpt2.make_task(cfg, mesh=mesh_tp)
    trainer_tp = Trainer(task_tp, cfg, mesh=mesh_tp)
    restored = CheckpointManager(str(tmp_path)).restore_latest(trainer_tp.state)
    assert restored is not None and int(restored[1]) == 3
    trainer_tp.state = restored[0]

    # Same params ⇒ same eval nll, computed under the TP layout.
    eval_ds = gpt2.eval_dataset(cfg)
    m_dp = trainer_dp.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    m_tp = trainer_tp.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    assert abs(m_dp["nll"] - m_tp["nll"]) < 1e-4, (m_dp, m_tp)
