"""GPT-2 workload end-to-end: tiny-config training on dp/tp/sp meshes."""

import jax
import numpy as np
import pytest

from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
from tensorflow_examples_tpu.data.memory import eval_batches, train_iterator
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import gpt2


def tiny_config(**kw):
    base = dict(
        vocab_size=64,
        seq_len=16,
        num_layers=2,
        num_heads=4,
        d_model=32,
        dropout=0.0,
        attention="xla",
        global_batch_size=16,
        train_steps=30,
        warmup_steps=5,
        learning_rate=3e-3,
        log_every=10,
        checkpoint_every=0,
        eval_every=0,
        precision="f32",
    )
    base.update(kw)
    return gpt2.Gpt2Config(**base)


def run_tiny(cfg, mesh):
    task = gpt2.make_task(cfg, mesh=mesh)
    trainer = Trainer(task, cfg, mesh=mesh)
    train_ds, _ = gpt2.datasets(cfg)
    it = train_iterator(train_ds, cfg.global_batch_size, seed=0)
    first = None
    state, metrics = trainer.state, None
    for _ in range(cfg.train_steps):
        state, metrics = trainer._train_step(state, trainer._put_batch(next(it)))
        if first is None:
            first = float(metrics["loss"])
    trainer.state = state
    return first, float(metrics["loss"]), trainer


def test_loss_decreases_dp(mesh8):
    first, last, _ = run_tiny(tiny_config(), mesh8)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.2, f"no learning: {first} -> {last}"


def test_loss_decreases_tp_sp():
    """TP over `model` + ring attention over `context`, one jitted step."""
    mesh = create_mesh(MeshConfig(data=2, model=2, context=2))
    cfg = tiny_config(attention="ring", train_steps=20)
    first, last, _ = run_tiny(cfg, mesh)
    assert last < first - 0.1, f"no learning: {first} -> {last}"


def test_tp_matches_dp_step():
    """One train step under TP must match the pure-DP step numerically."""
    cfg = tiny_config(train_steps=3)
    mesh_dp = create_mesh(MeshConfig(data=8))
    mesh_tp = create_mesh(MeshConfig(data=2, model=4))
    _, loss_dp, _ = run_tiny(cfg, mesh_dp)
    _, loss_tp, _ = run_tiny(cfg, mesh_tp)
    # f32 reduction-order noise across TP layouts is backend-dependent
    # (CPU XLA lands ~1.2e-3 after 3 steps); 2e-3 keeps the parity claim
    # while tolerating the summation-order delta.
    assert abs(loss_dp - loss_tp) < 2e-3, (loss_dp, loss_tp)


def test_fsdp_matches_dp_step():
    """Training under fsdp=4 (ZeRO-3-style param sharding + all-gather on
    use) must match pure DP numerically, and params must actually land
    sharded on the fsdp axis (VERDICT r1: declared but never trained)."""
    cfg = tiny_config(train_steps=3)
    mesh_dp = create_mesh(MeshConfig(data=8))
    mesh_fsdp = create_mesh(MeshConfig(data=2, fsdp=4))
    _, loss_dp, _ = run_tiny(cfg, mesh_dp)
    _, loss_fsdp, trainer = run_tiny(cfg, mesh_fsdp)
    assert abs(loss_dp - loss_fsdp) < 1e-3, (loss_dp, loss_fsdp)
    qkv = trainer.state.params["h_0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec[0] == "fsdp", qkv.sharding.spec
    # Sharded for real: each device holds 1/4 of the rows.
    shard = qkv.addressable_shards[0].data
    assert shard.shape[0] == qkv.shape[0] // 4, (shard.shape, qkv.shape)


def test_eval_and_fused_ce(mesh8):
    cfg = tiny_config(train_steps=5, fused_ce=True)
    _, _, trainer = run_tiny(cfg, mesh8)
    eval_ds = gpt2.eval_dataset(cfg)
    metrics = trainer.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    assert "nll" in metrics and np.isfinite(metrics["nll"])


def test_grad_accumulation_parity(mesh8):
    """accum=2 over half-batches must equal one update over the combined
    batch (VERDICT r1: the old test asserted only finiteness). Schedule
    horizons are micro-step counts rescaled by accum (optimizers._updates),
    so (steps=6, warmup=2, accum=2) and (steps=3, warmup=1) tick the same
    1-warmup/3-decay schedule."""
    cfg_acc = tiny_config(
        train_steps=6, warmup_steps=2, global_batch_size=8, grad_accum_steps=2
    )
    cfg_big = tiny_config(train_steps=3, warmup_steps=1, global_batch_size=16)
    ds, _ = gpt2.datasets(cfg_acc)
    it = train_iterator(ds, 8, seed=0)
    halves = [next(it) for _ in range(6)]
    pairs = [
        {
            k: np.concatenate([halves[2 * i][k], halves[2 * i + 1][k]])
            for k in halves[0]
        }
        for i in range(3)
    ]

    def run(cfg, batches):
        trainer = Trainer(gpt2.make_task(cfg, mesh=mesh8), cfg, mesh=mesh8)
        state = trainer.state
        for b in batches:
            state, _ = trainer._train_step(state, trainer._put_batch(b))
        return state.params

    p_acc = run(cfg_acc, halves)
    p_big = run(cfg_big, pairs)
    for a, b in zip(jax.tree.leaves(p_acc), jax.tree.leaves(p_big)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-5
        )


def test_pipeline_parallel_matches_sequential():
    """GPipe pipelined block stack == sequential application, fwd + grad."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.models import transformer
    from tensorflow_examples_tpu.parallel.pipeline import pipeline_apply

    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    mcfg = transformer.TransformerConfig(
        vocab_size=64, max_len=16, num_layers=4, num_heads=2, d_model=16,
        dropout=0.0, attention="xla",
    )
    blocks = transformer.init_stacked_blocks(mcfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16), jnp.float32)

    ref = transformer.apply_stacked_blocks(mcfg, blocks, x)
    stage_params = jax.tree.map(
        lambda p: p.reshape((4, 1) + p.shape[1:]), blocks
    )
    fn = lambda sp, h: pipeline_apply(
        lambda p, y: transformer.apply_stacked_blocks(mcfg, p, y),
        sp, h, mesh=mesh, num_microbatches=4,
    )
    out = jax.jit(fn)(stage_params, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    g_ref = jax.grad(lambda b: jnp.sum(
        transformer.apply_stacked_blocks(mcfg, b, x) ** 2))(blocks)
    g_pp = jax.jit(jax.grad(lambda sp: jnp.sum(fn(sp, x) ** 2)))(stage_params)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a).reshape(np.asarray(b).shape), np.asarray(b), atol=5e-4
        )


def test_pp_dropout_trains():
    """Dropout under PP (restriction lifted): per-(stage, microbatch)
    folded rngs; the run still learns."""
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    cfg = tiny_config(
        num_layers=4, dropout=0.1, train_steps=25, num_microbatches=4
    )
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.05, f"no learning: {first} -> {last}"


def test_pp_pretrained_layout_matches_dense():
    """stack_params_for_pipeline (the --pretrained-under-PP converter):
    a standard Transformer param tree re-laid into embed+stacked-blocks
    must produce identical logits through the pipeline path."""
    import jax.numpy as jnp

    from tensorflow_examples_tpu.models import transformer
    from tensorflow_examples_tpu.parallel.pipeline import pipeline_apply

    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    cfg = tiny_config(num_layers=4)
    mcfg = gpt2.model_config(cfg)
    model = transformer.Transformer(mcfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 16)), jnp.int32
    )
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    ref = model.apply({"params": params}, tokens)

    pp = transformer.stack_params_for_pipeline(params, cfg.num_layers)
    embed_head = transformer.EmbedHead(mcfg)
    x = embed_head.apply({"params": pp["embed"]}, tokens, method="encode")
    sp = jax.tree.map(lambda p: p.reshape((4, 1) + p.shape[1:]), pp["blocks"])
    x = jax.jit(
        lambda sp, x: pipeline_apply(
            lambda s, h: transformer.apply_stacked_blocks(mcfg, s, h),
            sp, x, mesh=mesh, num_microbatches=4,
        )
    )(sp, x)
    out = embed_head.apply({"params": pp["embed"]}, x, method="logits")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=3e-5, rtol=1e-5
    )


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
def test_loss_decreases_pp(schedule):
    """End-to-end pipelined training through the shared loop, both
    schedules (1F1B is the default; GPipe kept as the fallback)."""
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    cfg = tiny_config(
        num_layers=4, train_steps=20, num_microbatches=4,
        pipeline_schedule=schedule,
    )
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.1, f"no learning: {first} -> {last}"


def test_pp_1f1b_matches_gpipe_loss_and_grads():
    """The 1F1B schedule's explicit in-schedule gradients must equal the
    GPipe schedule's transpose-derived gradients on the identical param
    tree and batch (both equal the sequential model by transitivity with
    test_pp_pretrained_layout_matches_dense)."""
    import dataclasses as dc

    import jax.numpy as jnp

    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    cfg = tiny_config(num_layers=4, num_microbatches=4)
    t_1f1b = gpt2.make_task(dc.replace(cfg, pipeline_schedule="1f1b"), mesh=mesh)
    t_gpipe = gpt2.make_task(dc.replace(cfg, pipeline_schedule="gpipe"), mesh=mesh)
    params = t_1f1b.init_fn(jax.random.PRNGKey(0))["params"]
    rng = jax.random.PRNGKey(7)
    tokens = np.random.default_rng(3).integers(
        0, cfg.vocab_size, (cfg.global_batch_size, cfg.seq_len + 1)
    )
    batch = {"tokens": jnp.asarray(tokens, jnp.int32)}

    def value_grad(task):
        def f(p):
            loss, _, _ = task.loss_fn(p, {}, batch, rng=rng, train=True)
            return loss

        return jax.jit(jax.value_and_grad(f))(params)

    with mesh:
        loss_a, grads_a = value_grad(t_1f1b)
        loss_b, grads_b = value_grad(t_gpipe)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(grads_a), jax.tree.leaves(grads_b)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-4
        )


def test_1f1b_schedule_tables():
    """Schedule simulator invariants (asserted inside) plus the shape
    of the result: v=1 reproduces the round-3 tick count exactly, and
    interleaving shrinks the bubble in full-stage units (each v-chunk
    tick costs 1/v of a full-stage tick)."""
    from tensorflow_examples_tpu.parallel.pipeline import _schedule_1f1b

    op, mb, ch, t1, depth1, qf, qb = _schedule_1f1b(8, 4, 1)
    assert t1 == 22 and depth1 == 4 and qf == 2 and qb == 2  # 2m+2(P-1)
    assert (ch == 0).all()
    bubbles = {}
    for v in (1, 2, 4):
        *_, t, depth, _, _ = _schedule_1f1b(8, 4, v)
        bubbles[v] = (t - 2 * 8 * v) / v  # full-stage units
        assert depth <= min(8, 2 * 4)
    assert bubbles[2] < bubbles[1] and bubbles[4] < bubbles[2], bubbles


def test_pp_interleaved_matches_plain_1f1b():
    """Interleaved 1F1B (v=2, slot-major storage) must produce the same
    loss and gradients as plain 1F1B on the same logical params — the
    chunked schedule changes the execution order and placement, not the
    math. Blocks gradients are compared through the layer-row
    permutation that maps slot-major storage back to logical order."""
    import jax.numpy as jnp

    from tensorflow_examples_tpu.parallel.pipeline import interleave_perm

    p_dev, v = 2, 2
    mesh = create_mesh(MeshConfig(data=4, pipe=p_dev))
    cfg1 = tiny_config(num_layers=4, num_microbatches=4)
    cfg2 = tiny_config(num_layers=4, num_microbatches=4, pipe_interleave=v)
    t1 = gpt2.make_task(cfg1, mesh=mesh)
    t2 = gpt2.make_task(cfg2, mesh=mesh)
    params1 = t1.init_fn(jax.random.PRNGKey(0))["params"]
    per = cfg1.num_layers // (p_dev * v)
    row_perm = np.concatenate(
        [
            np.arange(s * per, (s + 1) * per)
            for s in interleave_perm(p_dev, v)
        ]
    )
    # Slot-major storage lives under a layout-stamped key (checkpoint
    # cross-(P, v) restore guard).
    slot_key = f"blocks_slotmajor_p{p_dev}v{v}"
    params2 = {
        "embed": params1["embed"],
        slot_key: jax.tree.map(lambda x: x[row_perm], params1["blocks"]),
    }
    rng = jax.random.PRNGKey(7)
    tokens = np.random.default_rng(3).integers(
        0, cfg1.vocab_size, (cfg1.global_batch_size, cfg1.seq_len + 1)
    )
    batch = {"tokens": jnp.asarray(tokens, jnp.int32)}

    def value_grad(task, params):
        def f(p):
            loss, _, _ = task.loss_fn(p, {}, batch, rng=rng, train=True)
            return loss

        return jax.jit(jax.value_and_grad(f))(params)

    with mesh:
        loss1, g1 = value_grad(t1, params1)
        loss2, g2 = value_grad(t2, params2)
        # Eval path (GPipe over un-permuted storage) must agree too.
        # (jit'd: partial-manual shard_map is a jit-context construct,
        # same as the Trainer's eval step.)
        ev1 = jax.jit(lambda p: t1.eval_fn(p, {}, batch))(params1)
        ev2 = jax.jit(lambda p: t2.eval_fn(p, {}, batch))(params2)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    np.testing.assert_allclose(
        float(ev1["nll"]), float(ev2["nll"]), rtol=1e-5
    )
    g2_logical = jax.tree.map(
        lambda x: x[np.argsort(row_perm)], g2[slot_key]
    )
    for a, b in zip(
        jax.tree.leaves(g1["blocks"]), jax.tree.leaves(g2_logical)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-4
        )
    for a, b in zip(
        jax.tree.leaves(g1["embed"]), jax.tree.leaves(g2["embed"])
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-4
        )


def test_pp_interleaved_trains():
    """End-to-end interleaved-1F1B training (with dropout rng folding
    per virtual stage) through the shared loop still learns."""
    mesh = create_mesh(MeshConfig(data=4, pipe=2))
    cfg = tiny_config(
        num_layers=4, dropout=0.1, train_steps=25, num_microbatches=4,
        pipe_interleave=2,
    )
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.05, f"no learning: {first} -> {last}"


@pytest.mark.parametrize("attention", ["xla", "flash"])
def test_pp_composes_with_tp(attention):
    """PP×TP (the partial-manual shard_map composition): the identical
    pipeline param tree must produce the same loss and gradients on a
    dp×pipe mesh and a dp×model×pipe mesh — TP inside the stages changes
    the partitioning, not the math. Also asserts the stacked weights
    actually shard over `model` (it must be real TP, not replication).
    attention="flash" exercises the round-4 nested model-axis shard_map
    inside the pipe-manual stages (the Pallas call no longer forces
    head gathers)."""
    import jax.numpy as jnp

    cfg = tiny_config(num_layers=4, num_microbatches=4, attention=attention)
    mesh_pp = create_mesh(MeshConfig(data=4, pipe=2))
    mesh_pptp = create_mesh(MeshConfig(data=2, model=2, pipe=2))
    t_pp = gpt2.make_task(cfg, mesh=mesh_pp)
    t_pptp = gpt2.make_task(cfg, mesh=mesh_pptp)
    params = t_pp.init_fn(jax.random.PRNGKey(0))["params"]
    rng = jax.random.PRNGKey(7)
    tokens = np.random.default_rng(3).integers(
        0, cfg.vocab_size, (cfg.global_batch_size, cfg.seq_len + 1)
    )
    batch = {"tokens": jnp.asarray(tokens, jnp.int32)}

    from tensorflow_examples_tpu.core.sharding import (
        shard_params,
        shardings_for_params,
    )

    def value_grad(task, mesh):
        def f(p):
            loss, _, _ = task.loss_fn(p, {}, batch, rng=rng, train=True)
            return loss

        sharded = shard_params(params, mesh, task.sharding_rules)
        with mesh:
            return jax.jit(jax.value_and_grad(f))(sharded)

    loss_a, grads_a = value_grad(t_pp, mesh_pp)
    loss_b, grads_b = value_grad(t_pptp, mesh_pptp)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(grads_a), jax.tree.leaves(grads_b)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-4
        )
    # The TP rules really shard the stacked ff weight over `model`.
    spec = shardings_for_params(params, mesh_pptp, t_pptp.sharding_rules)[
        "blocks"
    ]["mlp_fc"]["kernel"].spec
    assert "model" in str(spec)


def test_loss_decreases_pp_tp():
    """End-to-end PP×TP training through the shared loop."""
    mesh = create_mesh(MeshConfig(data=2, model=2, pipe=2))
    cfg = tiny_config(num_layers=4, train_steps=20, num_microbatches=4)
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.1, f"no learning: {first} -> {last}"


def test_pp_bf16_compiles_and_learns():
    """PP under the bf16 precision policy (the CLI default). Regression
    guard: a bf16 psum inside the partial-manual pipe region aborts this
    jaxlib's CPU compiler — _psum_pipe routes those reduces through f32
    (parallel/pipeline.py)."""
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    cfg = tiny_config(
        num_layers=4, train_steps=15, num_microbatches=4, precision="bf16"
    )
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.05, f"no learning: {first} -> {last}"


def test_moe_expert_parallel():
    """Switch-MoE GPT-2: aux loss present, learns, EP-sharded on mesh."""
    mesh = create_mesh(MeshConfig(data=2, model=4))
    cfg = tiny_config(moe_experts=4, train_steps=25, learning_rate=2e-3)
    task = gpt2.make_task(cfg, mesh=mesh)
    trainer = Trainer(task, cfg, mesh=mesh)
    train_ds, _ = gpt2.datasets(cfg)
    it = train_iterator(train_ds, cfg.global_batch_size, seed=0)
    losses = []
    state = trainer.state
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        losses.append(float(m["loss"]))
        assert np.isfinite(float(m["moe_aux"]))
        assert 0.0 <= float(m["moe_drop"]) <= 1.0
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1
    # Expert params must actually shard over the model axis.
    w_in = state.params["h_1"]["moe"]["w_in"]
    spec = w_in.sharding.spec
    assert spec and spec[0] == "model", spec


def test_moe_top2():
    """GShard-style top-2 routing: learns; drop fraction reported."""
    mesh = create_mesh(MeshConfig(data=2, model=4))
    cfg = tiny_config(
        moe_experts=4, moe_top_k=2, train_steps=20, learning_rate=2e-3
    )
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first - 0.1, f"no learning: {first} -> {last}"


def test_moe_router_gets_task_gradient():
    """top-1 gates must stay the raw router prob (Switch): renormalizing
    would make the gate constant 1.0 and detach the router from the
    task loss, leaving only the aux loss to train it."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.parallel.moe import moe_ffn

    rng = jax.random.PRNGKey(0)
    d, e, ff, n = 8, 4, 16, 32
    ks = jax.random.split(rng, 5)
    args = (
        jax.random.normal(ks[1], (e, d, ff)) * 0.1,
        jnp.zeros((e, ff)),
        jax.random.normal(ks[2], (e, ff, d)) * 0.1,
        jnp.zeros((e, d)),
        jax.random.normal(ks[3], (1, n, d)),
    )

    def task_loss(gate_w, top_k):
        out, _, _ = moe_ffn(gate_w, *args, top_k=top_k)
        return jnp.sum(out**2)

    gate_w = jax.random.normal(ks[0], (d, e))
    for k in (1, 2):
        g = jax.grad(task_loss)(gate_w, k)
        assert float(jnp.abs(g).max()) > 1e-6, (k, g)


def test_moe_capacity_overflow_drops():
    """With capacity_factor << 1 most assignments must drop (the metric
    actually measures overflow) while the residual keeps loss finite.
    Capacity/drop semantics live in the scatter formulation (the EP
    transport's reference); the grouped default is DROPLESS and must
    report exactly zero drops at any capacity."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.parallel.moe import moe_ffn

    rng = jax.random.PRNGKey(0)
    d, e, ff, n = 8, 4, 16, 64
    ks = jax.random.split(rng, 5)
    out, aux, drop = moe_ffn(
        jax.random.normal(ks[0], (d, e)),
        jax.random.normal(ks[1], (e, d, ff)) * 0.1,
        jnp.zeros((e, ff)),
        jax.random.normal(ks[2], (e, ff, d)) * 0.1,
        jnp.zeros((e, d)),
        jax.random.normal(ks[3], (1, n, d)),
        capacity_factor=0.1,
        impl="scatter",
    )
    assert out.shape == (1, n, d) and np.isfinite(np.asarray(out)).all()
    assert float(drop) > 0.5, float(drop)
    # The grouped path (the TPU default) never drops — even at absurd
    # capacity settings.
    _, _, drop_g = moe_ffn(
        jax.random.normal(ks[0], (d, e)),
        jax.random.normal(ks[1], (e, d, ff)) * 0.1,
        jnp.zeros((e, ff)),
        jax.random.normal(ks[2], (e, ff, d)) * 0.1,
        jnp.zeros((e, d)),
        jax.random.normal(ks[3], (1, n, d)),
        capacity_factor=0.1,
        impl="grouped",
    )
    assert float(drop_g) == 0.0, float(drop_g)
    # And with generous capacity the SCATTER capacity math drops
    # nothing (explicit impl: the backend-resolved default would pick
    # the grouped path on TPU, whose 0.0 is a tautology).
    _, _, drop2 = moe_ffn(
        jax.random.normal(ks[0], (d, e)),
        jax.random.normal(ks[1], (e, d, ff)) * 0.1,
        jnp.zeros((e, ff)),
        jax.random.normal(ks[2], (e, ff, d)) * 0.1,
        jnp.zeros((e, d)),
        jax.random.normal(ks[3], (1, n, d)),
        capacity_factor=float(e),
        impl="scatter",
    )
    assert float(drop2) == 0.0, float(drop2)


def test_tp_vocab_matches_dense():
    """Vocab-parallel fused CE == dense head CE (same seed, 3 steps)."""
    mesh = create_mesh(MeshConfig(data=2, model=4))
    cfg_dense = tiny_config(train_steps=3)
    cfg_tp = tiny_config(train_steps=3, tp_vocab=True)
    _, loss_dense, _ = run_tiny(cfg_dense, mesh)
    _, loss_tp, _ = run_tiny(cfg_tp, mesh)
    assert abs(loss_dense - loss_tp) < 1e-3, (loss_dense, loss_tp)


def test_tp_vocab_uneven_vocab():
    """Vocab not divisible by the model axis (padding path) still works."""
    mesh = create_mesh(MeshConfig(data=2, model=4))
    cfg = tiny_config(train_steps=4, tp_vocab=True, vocab_size=67)
    first, last, _ = run_tiny(cfg, mesh)
    assert np.isfinite(first) and np.isfinite(last)


def test_checkpoint_restores_across_mesh_layouts(tmp_path):
    """A checkpoint saved under pure-DP restores into a TP-sharded state:
    orbax re-lays arrays out to the live mesh (checkpoint.py claim)."""
    from tensorflow_examples_tpu.train.checkpoint import CheckpointManager

    cfg = tiny_config(train_steps=3)
    mesh_dp = create_mesh(MeshConfig(data=8))
    _, _, trainer_dp = run_tiny(cfg, mesh_dp)
    ckpt = CheckpointManager(str(tmp_path), async_save=False)
    ckpt.save(3, trainer_dp.state)
    ckpt.close()

    mesh_tp = create_mesh(MeshConfig(data=2, model=4))
    task_tp = gpt2.make_task(cfg, mesh=mesh_tp)
    trainer_tp = Trainer(task_tp, cfg, mesh=mesh_tp)
    restored = CheckpointManager(str(tmp_path)).restore_latest(trainer_tp.state)
    assert restored is not None and int(restored[1]) == 3
    trainer_tp.state = restored[0]

    # Same params ⇒ same eval nll, computed under the TP layout.
    eval_ds = gpt2.eval_dataset(cfg)
    m_dp = trainer_dp.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    m_tp = trainer_tp.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    assert abs(m_dp["nll"] - m_tp["nll"]) < 1e-4, (m_dp, m_tp)


def test_remat_policies_match_no_remat(mesh8):
    """--remat never changes numerics — only the memory/recompute
    trade. Each remat_policy's short trajectory must match the
    un-remat'd run (same seed, same data)."""
    runs = {}
    for name, over in {
        "plain": {},
        "none": dict(remat=True, remat_policy="none"),
        "dots": dict(remat=True, remat_policy="dots"),
        "dots_no_batch": dict(remat=True, remat_policy="dots_no_batch"),
    }.items():
        cfg = tiny_config(train_steps=3, **over)
        first, last, _ = run_tiny(cfg, mesh8)
        runs[name] = (first, last)
    for name, (first, last) in runs.items():
        assert abs(first - runs["plain"][0]) < 1e-5, (name, first, runs["plain"])
        assert abs(last - runs["plain"][1]) < 1e-4, (name, last, runs["plain"])


def test_remat_policy_validation(mesh8):
    cfg = tiny_config(train_steps=1, remat=True, remat_policy="bogus")
    with pytest.raises(ValueError, match="remat_policy"):
        run_tiny(cfg, mesh8)
