"""Context/tensor parallelism on the 8-fake-CPU-device mesh (SURVEY.md §4).

Ring and Ulysses attention under shard_map must match the full-sequence
XLA reference — forward and gradients — and the mesh_attention dispatcher
must route each mesh shape to a working implementation.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
from tensorflow_examples_tpu.ops.attention import attention_reference
from tensorflow_examples_tpu.parallel.attention import attention_spec, mesh_attention
from tensorflow_examples_tpu.parallel.ring import ring_attention, ulysses_attention


def qkv(b=2, h=4, s=32, d=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.fixture(scope="module")
def ctx_mesh():
    return create_mesh(MeshConfig(data=2, context=4))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_context_parallel_matches_reference(ctx_mesh, causal, fn):
    q, k, v = qkv()
    ref = attention_reference(q, k, v, causal=causal)
    local = functools.partial(fn, axis_name="context", causal=causal)
    spec = P("data", None, "context", None)
    out = jax.jit(
        jax.shard_map(
            local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_context_parallel_grads(ctx_mesh, fn):
    q, k, v = qkv(s=16)
    spec = P("data", None, "context", None)
    local = functools.partial(fn, axis_name="context", causal=True)
    sharded = jax.shard_map(
        local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )

    def loss(f, q, k, v):
        return jnp.sum(f(q, k, v) ** 2)

    g_ref = jax.grad(functools.partial(loss, attention_reference), argnums=(0, 1, 2))(
        q, k, v
    )
    g_out = jax.jit(
        jax.grad(functools.partial(loss, sharded), argnums=(0, 1, 2))
    )(q, k, v)
    for r, o in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o), atol=5e-4)


@pytest.mark.parametrize(
    "mesh_cfg,impl",
    [
        (MeshConfig(data=8), "flash"),
        (MeshConfig(data=2, model=4), "flash"),
        (MeshConfig(data=2, context=4), "ring"),
        (MeshConfig(data=2, context=4), "ulysses"),
        (MeshConfig(data=2, fsdp=2, context=2), "ring"),
    ],
)
def test_mesh_attention_dispatch(mesh_cfg, impl):
    mesh = create_mesh(mesh_cfg)
    q, k, v = qkv(b=8)
    ref = attention_reference(q, k, v, causal=True)
    sharding = NamedSharding(mesh, attention_spec(mesh))
    args = jax.device_put((q, k, v), sharding)
    out = jax.jit(
        functools.partial(mesh_attention, mesh=mesh, causal=True, impl=impl)
    )(*args)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


@pytest.mark.parametrize("zigzag", [True, False])
def test_ring_zigzag_and_contiguous_match_reference(ctx_mesh, zigzag):
    """Both causal ring schedules — zigzag (default) and contiguous with
    lax.cond hop skipping — against the full-sequence reference."""
    q, k, v = qkv(s=64, seed=3)
    ref = attention_reference(q, k, v, causal=True)
    local = functools.partial(
        ring_attention, axis_name="context", causal=True, zigzag=zigzag
    )
    spec = P("data", None, "context", None)
    sharded = jax.shard_map(
        local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = jax.jit(sharded)(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    def loss(f, q, k, v):
        return jnp.sum(f(q, k, v) ** 2)

    g_ref = jax.grad(functools.partial(loss, attention_reference), argnums=(0, 1, 2))(
        q, k, v
    )
    g_out = jax.jit(
        jax.grad(functools.partial(loss, sharded), argnums=(0, 1, 2))
    )(q, k, v)
    for r, o in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o), atol=5e-4)


def test_ring_odd_shard_falls_back_to_contiguous(ctx_mesh):
    """Auto zigzag must not fire on odd shard lengths (s=20 over c=4 →
    shard 5); the contiguous path covers it."""
    q, k, v = qkv(s=20, seed=5)
    ref = attention_reference(q, k, v, causal=True)
    local = functools.partial(ring_attention, axis_name="context", causal=True)
    spec = P("data", None, "context", None)
    out = jax.jit(
        jax.shard_map(
            local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_ring_causal_zigzag_costs_about_half_of_noncausal(ctx_mesh):
    """The load-balance claim, measured: a causal zigzag ring step should
    cost ~half the wall time of the non-causal ring at the same shape
    (causal attends half the pairs; the naive contiguous ring burned the
    full non-causal cost on causal inputs). Generous 0.8 bound — CPU
    interpret-mode timing is noisy, but 'no better than non-causal'
    (ratio ~1.0, the round-2 behavior) fails clearly."""
    import time

    q, k, v = qkv(b=1, h=2, s=2048, d=32, seed=7)
    spec = P(None, None, "context", None)

    def build(causal):
        local = functools.partial(
            ring_attention, axis_name="context", causal=causal
        )
        return jax.jit(
            jax.shard_map(
                local, mesh=ctx_mesh, in_specs=(spec, spec, spec),
                out_specs=spec, check_vma=False,
            )
        )

    def timeit(f):
        f(q, k, v).block_until_ready()  # compile
        best = float("inf")
        for _ in range(5):  # best-of-5: shields against CI load spikes
            t0 = time.perf_counter()
            f(q, k, v).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    t_causal = timeit(build(True))
    t_full = timeit(build(False))
    assert t_causal < 0.85 * t_full, (
        f"causal zigzag {t_causal:.4f}s vs non-causal {t_full:.4f}s "
        f"(ratio {t_causal / t_full:.2f}; expected ~0.5)"
    )


def test_mesh_attention_no_mesh():
    q, k, v = qkv()
    ref = attention_reference(q, k, v, causal=True)
    out = mesh_attention(q, k, v, mesh=None, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)
