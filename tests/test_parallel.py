"""Context/tensor parallelism on the 8-fake-CPU-device mesh (SURVEY.md §4).

Ring and Ulysses attention under shard_map must match the full-sequence
XLA reference — forward and gradients — and the mesh_attention dispatcher
must route each mesh shape to a working implementation.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
from tensorflow_examples_tpu.ops.attention import attention_reference
from tensorflow_examples_tpu.parallel.attention import attention_spec, mesh_attention
from tensorflow_examples_tpu.parallel.ring import ring_attention, ulysses_attention


def qkv(b=2, h=4, s=32, d=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.fixture(scope="module")
def ctx_mesh():
    return create_mesh(MeshConfig(data=2, context=4))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_context_parallel_matches_reference(ctx_mesh, causal, fn):
    q, k, v = qkv()
    ref = attention_reference(q, k, v, causal=causal)
    local = functools.partial(fn, axis_name="context", causal=causal)
    spec = P("data", None, "context", None)
    out = jax.jit(
        jax.shard_map(
            local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_context_parallel_grads(ctx_mesh, fn):
    q, k, v = qkv(s=16)
    spec = P("data", None, "context", None)
    local = functools.partial(fn, axis_name="context", causal=True)
    sharded = jax.shard_map(
        local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )

    def loss(f, q, k, v):
        return jnp.sum(f(q, k, v) ** 2)

    g_ref = jax.grad(functools.partial(loss, attention_reference), argnums=(0, 1, 2))(
        q, k, v
    )
    g_out = jax.jit(
        jax.grad(functools.partial(loss, sharded), argnums=(0, 1, 2))
    )(q, k, v)
    for r, o in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o), atol=5e-4)


@pytest.mark.parametrize(
    "mesh_cfg,impl",
    [
        (MeshConfig(data=8), "flash"),
        (MeshConfig(data=2, model=4), "flash"),
        (MeshConfig(data=2, context=4), "ring"),
        (MeshConfig(data=2, context=4), "ulysses"),
        (MeshConfig(data=2, fsdp=2, context=2), "ring"),
    ],
)
def test_mesh_attention_dispatch(mesh_cfg, impl):
    mesh = create_mesh(mesh_cfg)
    q, k, v = qkv(b=8)
    ref = attention_reference(q, k, v, causal=True)
    sharding = NamedSharding(mesh, attention_spec(mesh))
    args = jax.device_put((q, k, v), sharding)
    out = jax.jit(
        functools.partial(mesh_attention, mesh=mesh, causal=True, impl=impl)
    )(*args)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


@pytest.mark.parametrize(
    "mesh_cfg",
    [MeshConfig(data=8), MeshConfig(data=2, model=4), MeshConfig(model=8)],
)
def test_mesh_decode_attention_matches_reference(mesh_cfg):
    """Flash-decode under shard_map (batch over data, heads over model)
    must match the masked-cache XLA reference — the TP decode path."""
    from tensorflow_examples_tpu.ops.decode import decode_attention_reference
    from tensorflow_examples_tpu.parallel.attention import (
        decode_spec,
        mesh_decode_attention,
    )

    mesh = create_mesh(mesh_cfg)
    b, h, max_len, d = 8, 8, 64, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, max_len, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, max_len, d))
    length = jnp.asarray(37)
    ref = decode_attention_reference(q, k, v, length)
    sharding = NamedSharding(mesh, decode_spec(mesh, b, h))
    qs, ks, vs = jax.device_put((q, k, v), sharding)
    out = jax.jit(functools.partial(mesh_decode_attention, mesh=mesh))(
        qs, ks, vs, length
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_generate_under_tp_mesh_matches_single_device():
    """End-to-end sampling with a dp×tp mesh: greedy generate through the
    sharded flash-decode path must reproduce the meshless output."""
    from tensorflow_examples_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=97, max_len=32, num_layers=2, num_heads=4,
        d_model=16, dropout=0.0, attention="flash",
    )
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 97, (2, 4)), jnp.int32
    )
    plain = transformer.Transformer(cfg)
    params = plain.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    want = transformer.generate(
        plain, params, tokens, num_tokens=6,
        rng=jax.random.PRNGKey(1), temperature=0.0,
    )
    mesh = create_mesh(MeshConfig(data=2, model=4))
    meshed = transformer.Transformer(cfg, mesh=mesh)
    got = transformer.generate(
        meshed, params, tokens, num_tokens=6,
        rng=jax.random.PRNGKey(1), temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


class TestExplicitEP:
    """moe_ffn_ep: all-to-all expert dispatch vs the single-program path."""

    def _args(self, e=8, d=16, ff=32, b=8, s=16, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 6)
        return (
            jax.random.normal(ks[0], (d, e)) * 0.5,
            jax.random.normal(ks[1], (e, d, ff)) * 0.1,
            jax.random.normal(ks[2], (e, ff)) * 0.01,
            jax.random.normal(ks[3], (e, ff, d)) * 0.1,
            jax.random.normal(ks[4], (e, d)) * 0.01,
            jax.random.normal(ks[5], (b, s, d)),
        )

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_single_program(self, top_k):
        """With capacity ample enough that nothing drops, the explicit
        all-to-all dispatch must reproduce moe_ffn exactly (same math,
        different transport)."""
        from tensorflow_examples_tpu.parallel.moe import moe_ffn, moe_ffn_ep

        mesh = create_mesh(MeshConfig(data=2, model=4))
        args = self._args()
        kw = dict(capacity_factor=8.0, top_k=top_k, rng=None)
        want, aux_w, drop_w = moe_ffn(*args, **kw)
        got, aux_g, drop_g = jax.jit(
            functools.partial(moe_ffn_ep, mesh=mesh, **kw)
        )(*args)
        np.testing.assert_allclose(
            np.asarray(want), np.asarray(got), atol=2e-5, rtol=2e-5
        )
        np.testing.assert_allclose(float(aux_w), float(aux_g), rtol=1e-5)
        assert float(drop_w) == 0.0 and float(drop_g) == 0.0

    def test_grads_match_single_program(self):
        from tensorflow_examples_tpu.parallel.moe import moe_ffn, moe_ffn_ep

        mesh = create_mesh(MeshConfig(data=2, model=4))
        args = self._args(b=4, s=8)
        kw = dict(capacity_factor=8.0, top_k=2, rng=None)

        def loss(fn, *a):
            out, aux, _ = fn(*a, **kw)
            return jnp.sum(out**2) + 0.01 * aux

        g_ref = jax.grad(functools.partial(loss, moe_ffn), argnums=(0, 1, 3, 5))(
            *args
        )
        g_ep = jax.jit(
            jax.grad(
                functools.partial(
                    loss, functools.partial(moe_ffn_ep, mesh=mesh)
                ),
                argnums=(0, 1, 3, 5),
            )
        )(*args)
        for r, o, name in zip(g_ref, g_ep, ("gate", "w_in", "w_out", "x")):
            np.testing.assert_allclose(
                np.asarray(r), np.asarray(o), atol=5e-4, rtol=5e-4,
                err_msg=f"d{name}",
            )

    def test_dispatch_is_all_to_all(self):
        """The point of the explicit path: the compiled HLO must exchange
        tokens with all-to-all, not all-gather the dispatch buffers."""
        from tensorflow_examples_tpu.parallel.moe import moe_ffn_ep

        mesh = create_mesh(MeshConfig(data=2, model=4))
        args = self._args()
        hlo = (
            jax.jit(
                functools.partial(
                    moe_ffn_ep, mesh=mesh, capacity_factor=2.0, top_k=2
                )
            )
            .lower(*args)
            .compile()
            .as_text()
        )
        assert "all-to-all" in hlo

    def test_ep_indivisible_token_dims_replicate(self):
        """Decode-time shapes — batch 1, single-token step — must not
        trace-fail on a mesh with batch/context axes: the token spec
        drops non-dividing axes and replicates (only the `model`
        all-to-all is essential)."""
        from tensorflow_examples_tpu.parallel.moe import moe_ffn, moe_ffn_ep

        mesh = create_mesh(MeshConfig(data=2, model=4))
        args = self._args(b=1, s=1)
        kw = dict(capacity_factor=8.0, top_k=2, rng=None)
        want, _, _ = moe_ffn(*args, **kw)
        got, _, _ = jax.jit(functools.partial(moe_ffn_ep, mesh=mesh, **kw))(
            *args
        )
        np.testing.assert_allclose(
            np.asarray(want), np.asarray(got), atol=2e-5, rtol=2e-5
        )

    def test_moe_generate_under_mesh(self):
        """End-to-end: greedy sampling from an MoE model on a dp×tp mesh
        (the MoeMlp auto-EP path at decode shapes) matches meshless."""
        from tensorflow_examples_tpu.models import transformer

        cfg = transformer.TransformerConfig(
            vocab_size=97, max_len=16, num_layers=2, num_heads=4,
            d_model=16, dropout=0.0, attention="flash",
            moe_experts=8, moe_every=2, moe_top_k=2,
            moe_capacity_factor=4.0,
        )
        prompt = jnp.asarray([[5, 17, 3]], jnp.int32)  # batch 1
        plain = transformer.Transformer(cfg)
        params = plain.init({"params": jax.random.PRNGKey(0)}, prompt)["params"]
        want = transformer.generate(
            plain, params, prompt, num_tokens=4,
            rng=jax.random.PRNGKey(1), temperature=0.0,
        )
        mesh = create_mesh(MeshConfig(data=2, model=4))
        got = transformer.generate(
            transformer.Transformer(cfg, mesh=mesh), params, prompt,
            num_tokens=4, rng=jax.random.PRNGKey(1), temperature=0.0,
        )
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    def test_gmm_tiling_respects_row_divisibility(self):
        """gmm's make_group_metadata requires tm | m; the adaptive
        tiling must halve tm until it divides, prefer a tk that DIVIDES
        k (768 takes 384-wide tiles exactly; a capped 512 leaves a
        masked 256 remainder tile every pass), and pick the large tiles
        at the bench shape (the whole point — 128^3 at
        [16384, 768, 3072] is ~19k grid steps of overhead)."""
        from tensorflow_examples_tpu.parallel.moe import (
            GMM_TILE_CAP, _gmm_tiling,
        )

        cap = GMM_TILE_CAP
        assert _gmm_tiling(16384, 768, 3072) == (cap, 384, cap)
        assert _gmm_tiling(256, 128, 128) == (256, 128, 128)
        assert _gmm_tiling(256, 3072, 3072) == (256, cap, cap)  # cap | k
        # No lane-aligned divisor <= cap: fall back to min(cap, k).
        assert _gmm_tiling(256, 64, 64) == (256, 64, 64)
        # No divisor in [cap/2, cap] either (640's largest is 128):
        # one near-cap masked pass beats five tiny exact ones.
        assert _gmm_tiling(256, 640, 640) == (256, cap, cap)
        m, k, n = 384, 768, 3072  # m = 3·128: cap halves to 128
        tm, tk, tn = _gmm_tiling(m, k, n)
        assert m % tm == 0 and tm == 128
        assert k % tk == 0 and tk <= k and tn <= n

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_grouped_matches_scatter_impl(self, top_k):
        """The sort-based dropless ragged_dot path (the TPU hot path)
        must compute the same function as the static-capacity
        scatter/gather reference when nothing drops — outputs, aux
        loss, and grads (VERDICT r4 weak #3 rewrite)."""
        from tensorflow_examples_tpu.parallel.moe import moe_ffn

        args = self._args()
        kw = dict(capacity_factor=8.0, top_k=top_k, rng=None)
        want, aux_w, _ = moe_ffn(*args, impl="scatter", **kw)
        got, aux_g, drop_g = jax.jit(
            functools.partial(moe_ffn, impl="grouped", **kw)
        )(*args)
        np.testing.assert_allclose(
            np.asarray(want), np.asarray(got), atol=2e-5, rtol=2e-5
        )
        np.testing.assert_allclose(float(aux_w), float(aux_g), rtol=1e-5)
        assert float(drop_g) == 0.0  # dropless by construction

        def loss(impl, *a):
            out, aux, _ = moe_ffn(*a, impl=impl, **kw)
            return jnp.sum(out**2) + 0.01 * aux

        g_ref = jax.grad(
            functools.partial(loss, "scatter"), argnums=(0, 1, 3, 5)
        )(*args)
        g_new = jax.jit(
            jax.grad(
                functools.partial(loss, "grouped"), argnums=(0, 1, 3, 5)
            )
        )(*args)
        for r, o, name in zip(g_ref, g_new, ("gate", "w_in", "w_out", "x")):
            np.testing.assert_allclose(
                np.asarray(r), np.asarray(o), atol=5e-4, rtol=5e-4,
                err_msg=f"d{name}",
            )

    def test_sorted_capacity_slotting_invariants(self):
        """_capacity_slots_sorted under OVERFLOW: the pair<->slot maps
        stay mutually inverse bijections on the kept set, the buffer
        holds exactly the kept tokens, and the kept count is
        sum_e min(count_e, capacity)."""
        import numpy as np

        from tensorflow_examples_tpu.parallel.moe import (
            _capacity_slots_sorted,
        )

        rng = np.random.default_rng(0)
        # cap 14 vs per-expert pair counts [12, 9, 27, 18] (this seed):
        # two experts UNDERFILL (invalid-slot branch) and two OVERFLOW
        # (dropped-pair branch) — both sides of the quota exercised.
        n, d, e, top_k, cap = 33, 5, 4, 2, 14
        tokens = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
        experts = [
            jnp.asarray(rng.integers(0, e, n), jnp.int32)
            for _ in range(top_k)
        ]
        xin, pair_slot, pair_keep, slot_pair, slot_valid, kept = (
            _capacity_slots_sorted(tokens, experts, top_k, e, cap)
        )
        eid = np.stack([np.asarray(x) for x in experts], 1).reshape(-1)
        counts = np.bincount(eid, minlength=e)
        assert int(kept) == int(np.minimum(counts, cap).sum())
        ps, pk = np.asarray(pair_slot), np.asarray(pair_keep)
        sp, sv = np.asarray(slot_pair), np.asarray(slot_valid)
        x = np.asarray(xin)
        filled = 0
        for slot in range(e * cap):
            if not sv[slot]:
                # invalid slots are zero and (if in range) not claimed
                assert np.all(x[slot] == 0)
                continue
            p = sp[slot]
            assert pk[p] and ps[p] == slot  # inverse bijection
            assert eid[p] == slot // cap  # right expert's queue
            np.testing.assert_array_equal(
                x[slot], np.asarray(tokens)[p // top_k]
            )
            filled += 1
        assert filled == int(kept)
        # every kept pair's slot points back at it
        for p in np.nonzero(pk)[0]:
            assert sv[ps[p]] and sp[ps[p]] == p

    def test_ep_fallback_without_model_axis(self):
        """E % model != 0 (or model == 1) must fall through to the
        single-program path and still be correct."""
        from tensorflow_examples_tpu.parallel.moe import moe_ffn, moe_ffn_ep

        mesh = create_mesh(MeshConfig(data=8))
        args = self._args(e=6)
        kw = dict(capacity_factor=8.0, top_k=1, rng=None)
        want, _, _ = moe_ffn(*args, **kw)
        got, _, _ = moe_ffn_ep(*args, mesh=mesh, **kw)
        np.testing.assert_allclose(
            np.asarray(want), np.asarray(got), atol=2e-5, rtol=2e-5
        )


@pytest.mark.parametrize("zigzag", [True, False])
def test_ring_zigzag_and_contiguous_match_reference(ctx_mesh, zigzag):
    """Both causal ring schedules — zigzag (default) and contiguous with
    lax.cond hop skipping — against the full-sequence reference."""
    q, k, v = qkv(s=64, seed=3)
    ref = attention_reference(q, k, v, causal=True)
    local = functools.partial(
        ring_attention, axis_name="context", causal=True, zigzag=zigzag
    )
    spec = P("data", None, "context", None)
    sharded = jax.shard_map(
        local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = jax.jit(sharded)(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    def loss(f, q, k, v):
        return jnp.sum(f(q, k, v) ** 2)

    g_ref = jax.grad(functools.partial(loss, attention_reference), argnums=(0, 1, 2))(
        q, k, v
    )
    g_out = jax.jit(
        jax.grad(functools.partial(loss, sharded), argnums=(0, 1, 2))
    )(q, k, v)
    for r, o in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o), atol=5e-4)


def test_ring_odd_shard_falls_back_to_contiguous(ctx_mesh):
    """Auto zigzag must not fire on odd shard lengths (s=20 over c=4 →
    shard 5); the contiguous path covers it."""
    q, k, v = qkv(s=20, seed=5)
    ref = attention_reference(q, k, v, causal=True)
    local = functools.partial(ring_attention, axis_name="context", causal=True)
    spec = P("data", None, "context", None)
    out = jax.jit(
        jax.shard_map(
            local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_ring_causal_zigzag_costs_about_half_of_noncausal(ctx_mesh):
    """The load-balance claim, measured: a causal zigzag ring step should
    cost ~half the wall time of the non-causal ring at the same shape
    (causal attends half the pairs; the naive contiguous ring burned the
    full non-causal cost on causal inputs). Generous 0.8 bound — CPU
    interpret-mode timing is noisy, but 'no better than non-causal'
    (ratio ~1.0, the round-2 behavior) fails clearly."""
    import time

    q, k, v = qkv(b=1, h=2, s=2048, d=32, seed=7)
    spec = P(None, None, "context", None)

    def build(causal):
        local = functools.partial(
            ring_attention, axis_name="context", causal=causal
        )
        return jax.jit(
            jax.shard_map(
                local, mesh=ctx_mesh, in_specs=(spec, spec, spec),
                out_specs=spec, check_vma=False,
            )
        )

    def timeit(f):
        f(q, k, v).block_until_ready()  # compile
        best = float("inf")
        for _ in range(5):  # best-of-5: shields against CI load spikes
            t0 = time.perf_counter()
            f(q, k, v).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    t_causal = timeit(build(True))
    t_full = timeit(build(False))
    assert t_causal < 0.85 * t_full, (
        f"causal zigzag {t_causal:.4f}s vs non-causal {t_full:.4f}s "
        f"(ratio {t_causal / t_full:.2f}; expected ~0.5)"
    )


def test_mesh_attention_no_mesh():
    q, k, v = qkv()
    ref = attention_reference(q, k, v, causal=True)
    out = mesh_attention(q, k, v, mesh=None, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


@pytest.mark.parametrize("s", [20, 18])
def test_mesh_attention_pads_causal_to_zigzag(ctx_mesh, s):
    """VERDICT r3 item 7 (odd-shard corner closed at the wrapper):
    causal context-parallel shapes that previously took the unbalanced
    contiguous ring (s=20 over c=4 → odd shard 5) or could not shard at
    all (s=18, 18 % 4 != 0) are padded globally to the next multiple of
    2c. Tail pads are causally invisible to every real query, so
    outputs AND gradients must match the unpadded reference exactly."""
    q, k, v = qkv(s=s, seed=11)
    ref = attention_reference(q, k, v, causal=True)
    f = jax.jit(
        functools.partial(mesh_attention, mesh=ctx_mesh, causal=True)
    )
    out = f(q, k, v)
    assert out.shape == q.shape
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(
        functools.partial(loss, attention_reference), argnums=(0, 1, 2)
    )(q, k, v)
    g_out = jax.jit(
        jax.grad(functools.partial(loss, f), argnums=(0, 1, 2))
    )(q, k, v)
    for r, o in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o), atol=5e-4)
