"""Context/tensor parallelism on the 8-fake-CPU-device mesh (SURVEY.md §4).

Ring and Ulysses attention under shard_map must match the full-sequence
XLA reference — forward and gradients — and the mesh_attention dispatcher
must route each mesh shape to a working implementation.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
from tensorflow_examples_tpu.ops.attention import attention_reference
from tensorflow_examples_tpu.parallel.attention import attention_spec, mesh_attention
from tensorflow_examples_tpu.parallel.ring import ring_attention, ulysses_attention


def qkv(b=2, h=4, s=32, d=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.fixture(scope="module")
def ctx_mesh():
    return create_mesh(MeshConfig(data=2, context=4))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_context_parallel_matches_reference(ctx_mesh, causal, fn):
    q, k, v = qkv()
    ref = attention_reference(q, k, v, causal=causal)
    local = functools.partial(fn, axis_name="context", causal=causal)
    spec = P("data", None, "context", None)
    out = jax.jit(
        jax.shard_map(
            local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_context_parallel_grads(ctx_mesh, fn):
    q, k, v = qkv(s=16)
    spec = P("data", None, "context", None)
    local = functools.partial(fn, axis_name="context", causal=True)
    sharded = jax.shard_map(
        local, mesh=ctx_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )

    def loss(f, q, k, v):
        return jnp.sum(f(q, k, v) ** 2)

    g_ref = jax.grad(functools.partial(loss, attention_reference), argnums=(0, 1, 2))(
        q, k, v
    )
    g_out = jax.jit(
        jax.grad(functools.partial(loss, sharded), argnums=(0, 1, 2))
    )(q, k, v)
    for r, o in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o), atol=5e-4)


@pytest.mark.parametrize(
    "mesh_cfg,impl",
    [
        (MeshConfig(data=8), "flash"),
        (MeshConfig(data=2, model=4), "flash"),
        (MeshConfig(data=2, context=4), "ring"),
        (MeshConfig(data=2, context=4), "ulysses"),
        (MeshConfig(data=2, fsdp=2, context=2), "ring"),
    ],
)
def test_mesh_attention_dispatch(mesh_cfg, impl):
    mesh = create_mesh(mesh_cfg)
    q, k, v = qkv(b=8)
    ref = attention_reference(q, k, v, causal=True)
    sharding = NamedSharding(mesh, attention_spec(mesh))
    args = jax.device_put((q, k, v), sharding)
    out = jax.jit(
        functools.partial(mesh_attention, mesh=mesh, causal=True, impl=impl)
    )(*args)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_mesh_attention_no_mesh():
    q, k, v = qkv()
    ref = attention_reference(q, k, v, causal=True)
    out = mesh_attention(q, k, v, mesh=None, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)
