"""Telemetry layer tests (ISSUE 2).

Covers the core contracts — registry counter/gauge/histogram semantics,
span nesting + Chrome-trace export (golden file under a fake clock),
throughput/MFU/goodput math for the MNIST and GPT-2 shapes, the JSONL
line schema — and the wired behavior: a CPU MNIST smoke run producing a
schema-valid JSONL + a multi-span Chrome trace (the ISSUE 2 acceptance
criterion), final-window flushes on the preemption and bad-step abort
exit paths, the explicit null-writer fallback for the TensorBoard sink,
and the watchdog naming the open span in its hang dump.

Marked ``telemetry`` (and deliberately not ``slow``) so the tier-1
command always validates the observability layer it relies on.
"""

import json
import logging
import os
import re

import jax
import numpy as np
import pytest

from tensorflow_examples_tpu.data.memory import eval_batches, train_iterator
from tensorflow_examples_tpu.data.sources import synthetic_images
from tensorflow_examples_tpu.telemetry import accounting, schema
from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import sinks as sinks_mod
from tensorflow_examples_tpu.telemetry import spans as spans_mod
from tensorflow_examples_tpu.train import resilience
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.utils import faults as faults_mod
from tensorflow_examples_tpu.workloads import mnist

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


@pytest.fixture
def fresh_telemetry():
    """Isolated default registry + tracer for counting assertions."""
    reg = registry_mod.reset_default_registry()
    tracer = spans_mod.reset_default_tracer()
    yield reg, tracer
    registry_mod.reset_default_registry()
    spans_mod.reset_default_tracer()


def tiny_cfg(**kw):
    defaults = dict(
        device="cpu",
        global_batch_size=64,
        train_steps=12,
        log_every=4,
        learning_rate=1e-2,
        hidden=16,
        num_layers=1,
        dropout=0.0,
        precision="f32",
        checkpoint_every=6,
        watchdog_secs=0,
    )
    defaults.update(kw)
    return mnist.MnistConfig(**defaults)


def _data(n=256):
    return synthetic_images(n=n, shape=(28, 28, 1), num_classes=10, seed=0)


# ------------------------------------------------------------- registry


class TestRegistry:
    def test_counter_semantics(self):
        reg = registry_mod.MetricsRegistry()
        c = reg.counter("x")
        assert c is reg.counter("x")  # get-or-create returns the instance
        c.inc()
        c.inc(4)
        assert c.value == 5
        with pytest.raises(ValueError, match="must be >= 0"):
            c.inc(-1)
        assert reg.counter_values() == {"x": 5}

    def test_gauge_semantics(self):
        reg = registry_mod.MetricsRegistry()
        g = reg.gauge("g")
        assert g.value is None
        assert reg.gauge_values() == {}  # unset gauges don't emit
        g.set(2)
        g.set(3.5)
        assert reg.gauge_values() == {"g": 3.5}

    def test_histogram_semantics(self):
        reg = registry_mod.MetricsRegistry()
        h = reg.histogram("t")
        assert h.percentile(50) is None
        assert h.summary()["count"] == 0
        for v in [0.1, 0.2, 0.3, 0.4, 1.0]:
            h.record(v)
        s = h.summary()
        assert s["count"] == 5
        assert s["min"] == pytest.approx(0.1)
        assert s["max"] == pytest.approx(1.0)
        assert s["mean"] == pytest.approx(0.4)
        assert h.percentile(50) == pytest.approx(0.3)  # nearest-rank
        assert h.percentile(95) == pytest.approx(1.0)

    def test_histogram_sample_window_bounded(self):
        h = registry_mod.TimeHistogram("t", max_samples=4)
        for v in [10.0, 10.0, 1.0, 2.0, 3.0, 4.0]:
            h.record(v)
        assert h.count == 6  # aggregates cover the whole run...
        assert h.max == 10.0
        assert h.percentile(95) == 4.0  # ...percentiles the recent window

    def test_snapshot_and_merge(self):
        reg = registry_mod.MetricsRegistry()
        reg.counter("a").inc(2)
        reg.gauge("b").set(1.0)
        reg.histogram("c").record(0.5)
        snap = reg.snapshot()
        assert snap["counters"] == {"a": 2}
        assert snap["gauges"] == {"b": 1.0}
        assert snap["histograms"]["c"]["count"] == 1
        reg.merge_counter_values({"a": 3, "new": 7})
        assert reg.counter_values() == {"a": 5, "new": 7}


# ---------------------------------------------------------------- spans


class TestSpans:
    def test_nesting_feeds_histogram_and_active_names(self, fresh_telemetry):
        reg, tracer = fresh_telemetry
        seen_inside = []
        with tracer.span("outer"):
            with tracer.span("inner"):
                seen_inside.append(tracer.active_span_names())
        assert seen_inside == [["inner"]]  # innermost open span
        assert tracer.active_span_names() == []
        names = [e["name"] for e in tracer.events()]
        assert names == ["inner", "outer"]  # completion order
        assert reg.histogram("span/outer").count == 1
        assert reg.histogram("span/inner").count == 1

    def test_nesting_timestamps_contained(self):
        tracer = spans_mod.Tracer(registry_mod.MetricsRegistry())
        with tracer.span("outer"):
            with tracer.span("inner"):
                pass
        inner, outer = tracer.events()
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3

    def test_event_buffer_bounded(self):
        tracer = spans_mod.Tracer(
            registry_mod.MetricsRegistry(), max_events=2
        )
        for i in range(5):
            with tracer.span(f"s{i}"):
                pass
        assert len(tracer.events()) == 2  # first events kept
        assert tracer.dropped == 3
        assert tracer.chrome_trace()["droppedEventCount"] == 3

    def test_chrome_trace_golden(self):
        """Pin the export format byte-for-byte under a fake clock (thread
        id normalized — the one legitimately nondeterministic field)."""
        clock = iter(range(0, 100_000, 1000))  # 1µs ticks
        tracer = spans_mod.Tracer(
            registry_mod.MetricsRegistry(), now_ns=lambda: next(clock)
        )
        with tracer.span("step", step=3):
            with tracer.span("fetch"):
                pass
        trace = tracer.chrome_trace()
        for ev in trace["traceEvents"]:
            ev["tid"] = 0
        got = json.dumps(trace, indent=2, sort_keys=True) + "\n"
        golden_path = os.path.join(GOLDEN, "chrome_trace.json")
        with open(golden_path) as f:
            assert got == f.read(), (
                "chrome trace format drifted; if intentional, regenerate "
                f"{golden_path} with this test's `got` value"
            )


# ----------------------------------------------------------- accounting


class TestAccounting:
    def test_train_step_flops_mnist_shape(self):
        # Per-example workload: 6 * N * B (tokens_per_example = 1).
        assert accounting.train_step_flops(12_730, 256) == pytest.approx(
            6.0 * 12_730 * 256
        )

    def test_train_step_flops_gpt2_shape(self):
        # Token workload: 6 * N * B * S — GPT-2 124M at B=16, S=1024.
        n = 124_000_000
        assert accounting.train_step_flops(n, 16, 1024) == pytest.approx(
            6.0 * n * 16 * 1024
        )

    def test_mfu(self):
        # 100 GFLOP steps at 10/s on a 10 TFLOP/s chip = 10% MFU.
        assert accounting.mfu(100e9, 10.0, 10e12) == pytest.approx(0.1)
        assert accounting.mfu(0.0, 10.0, 10e12) is None
        assert accounting.mfu(100e9, 10.0, 0.0) is None

    def test_peak_table(self):
        peak, known = accounting.peak_flops_per_device("TPU v4")
        assert known and peak == 275e12
        peak, known = accounting.peak_flops_per_device("TPU v5 lite")
        assert known and peak == 197e12
        peak, known = accounting.peak_flops_per_device("cpu")
        assert not known and peak == accounting.DEFAULT_PEAK_FLOPS

    def test_goodput(self):
        assert accounting.goodput({}) is None  # nothing stepped yet
        assert accounting.goodput({"train/steps_total": 100}) == 1.0
        assert accounting.goodput(
            {
                "train/steps_total": 100,
                "resilience/bad_steps": 3,
                "resilience/steps_lost": 7,
            }
        ) == pytest.approx(0.90)


# ---------------------------------------------------------------- schema


class TestSchema:
    def _line(self, **over):
        line = {
            "schema_version": schema.SCHEMA_VERSION,
            "kind": "window",
            "host": 0,
            "step": 10,
            "time_unix": 1_700_000_000.0,
            "session_start_unix": 1_699_999_000.0,
            "metrics": {"train/loss": 1.5},
            "counters": {"train/steps_total": 10},
            "gauges": {},
            "derived": {"mfu": None, "goodput": 1.0},
        }
        line.update(over)
        return line

    def test_valid_line(self):
        assert schema.validate_line(self._line()) == []
        schema.validate(self._line())  # and the raising form passes

    def test_golden_v1_line_still_parses(self):
        """Pre-ISSUE-3 run dirs must keep validating: a frozen v1 line
        (no memory/compile/profile fields, v1 kinds only)."""
        v1 = {
            "schema_version": 1,
            "kind": "final",
            "step": 400,
            "time_unix": 1_760_000_000.0,
            "session_start_unix": 1_759_999_000.0,
            "metrics": {"train/loss": 2.31},
            "counters": {"train/steps_total": 400, "io/retries": 1},
            "gauges": {"telemetry/flops_per_step": 1.2e15},
            "derived": {"examples_per_sec": 51234.0, "mfu": 0.42,
                        "goodput": 1.0},
            "exit_reason": "complete",
        }
        assert schema.validate_line(v1) == []

    def test_v2_fields_rejected_on_v1_lines(self):
        assert any(
            "v2 field" in p
            for p in schema.validate_line(
                self._line(schema_version=1, memory={"live_bytes": 1})
            )
        )
        assert schema.validate_line(self._line(kind="memory",
                                               schema_version=1))

    def test_memory_kind_and_fields(self):
        # memory object optional on windows, required on memory lines.
        assert schema.validate_line(
            self._line(memory={"live_bytes": 100, "peak_live_bytes": 200})
        ) == []
        assert any(
            "missing the memory object" in p
            for p in schema.validate_line(self._line(kind="memory"))
        )
        assert schema.validate_line(
            self._line(kind="memory", memory={"params_bytes": 10})
        ) == []
        assert schema.validate_line(self._line(memory={"x": "big"}))

    def test_compile_warning_contract(self):
        good = self._line(
            kind="compile_warning",
            compile={"fn": "train_step", "delta": "axis 0: 64->32",
                     "count": 2, "wall_secs": 0.5},
        )
        assert schema.validate_line(good) == []
        assert any(
            "missing the compile object" in p
            for p in schema.validate_line(self._line(kind="compile_warning"))
        )
        assert schema.validate_line(
            self._line(kind="compile_warning", compile={"fn": "x"})
        )  # delta required
        # and the compile object is exclusive to compile_warning lines
        assert schema.validate_line(
            self._line(compile={"fn": "x", "delta": "y"})
        )

    def test_profile_object_final_only(self):
        prof = {"dir": "/tmp/p", "start_step": 10, "num_steps": 10,
                "wall_secs": 1.0}
        assert schema.validate_line(
            self._line(kind="final", exit_reason="complete", profile=prof)
        ) == []
        assert schema.validate_line(self._line(profile=prof))
        assert schema.validate_line(
            self._line(kind="final", exit_reason="complete",
                       profile={"dir": 3})
        )

    def test_v3_host_field_contract(self):
        """ISSUE 4: every v3 line carries the writing host's index; v1/
        v2 lines must not (a 'v2' line with one is mislabeled v3)."""
        assert schema.validate_line(self._line()) == []
        line = self._line()
        del line["host"]
        assert any("host" in p for p in schema.validate_line(line))
        assert schema.validate_line(self._line(host=-1))
        assert schema.validate_line(self._line(host=True))
        v2 = self._line(schema_version=2)
        assert any(
            "v3 field 'host'" in p for p in schema.validate_line(v2)
        )
        del v2["host"]
        assert schema.validate_line(v2) == []  # v2 without host: fine
        assert any(
            "v3 field 'fleet'" in p
            for p in schema.validate_line(dict(v2, fleet={"hosts": []}))
        )

    def _fleet(self, **over):
        fleet = {
            "hosts": [
                {"host": 0, "step_time_p50": 0.01, "step_time_p95": 0.011,
                 "data_fetch_p95": 0.001, "steps_lost": 0,
                 "peak_live_bytes": 1024, "io_retries": 0,
                 "batches_skipped": 0},
                {"host": 1, "step_time_p50": 0.01, "step_time_p95": 0.05,
                 "data_fetch_p95": 0.04, "steps_lost": 0,
                 "peak_live_bytes": 1024, "io_retries": 3,
                 "batches_skipped": 0},
            ],
            "slowest_host": 1,
            "skew": 4.5,
            "side": "input",
            "straggler": True,
        }
        fleet.update(over)
        return fleet

    def test_fleet_line_contract(self):
        good = self._line(kind="fleet", fleet=self._fleet())
        assert schema.validate_line(good) == []
        # nulls where a host had no data yet are fine
        assert schema.validate_line(
            self._line(kind="fleet", fleet=self._fleet(
                slowest_host=None, skew=None, side=None, straggler=False,
            ))
        ) == []
        # the fleet object is required on (and exclusive to) fleet lines
        assert any(
            "missing the fleet object" in p
            for p in schema.validate_line(self._line(kind="fleet"))
        )
        assert any(
            "non-fleet line" in p
            for p in schema.validate_line(self._line(fleet=self._fleet()))
        )
        # hosts must be a non-empty list of host-indexed objects
        assert schema.validate_line(
            self._line(kind="fleet", fleet=self._fleet(hosts=[]))
        )
        assert schema.validate_line(
            self._line(kind="fleet",
                       fleet=self._fleet(hosts=[{"step_time_p50": 1.0}]))
        )
        # every FLEET_HOST_KEYS entry is required (writer and validator
        # share the tuple — fleet.VECTOR_KEYS aliases the schema's
        # vector, whose required prefix is FLEET_HOST_KEYS; the
        # data_work_p95 extension is additive/optional so pre-ISSUE-6
        # lines keep validating)
        from tensorflow_examples_tpu.telemetry import fleet as fleet_mod

        assert fleet_mod.VECTOR_KEYS is schema.FLEET_VECTOR_KEYS
        assert schema.FLEET_VECTOR_KEYS[: len(schema.FLEET_HOST_KEYS)] == (
            schema.FLEET_HOST_KEYS
        )
        incomplete = dict(self._fleet()["hosts"][0])
        del incomplete["data_fetch_p95"]
        assert any(
            "missing 'data_fetch_p95'" in p
            for p in schema.validate_line(
                self._line(kind="fleet",
                           fleet=self._fleet(hosts=[incomplete]))
            )
        )
        assert schema.validate_line(
            self._line(kind="fleet", fleet=self._fleet(side="network"))
        )
        assert schema.validate_line(
            self._line(kind="fleet", fleet=self._fleet(skew="big"))
        )
        assert schema.validate_line(
            self._line(kind="fleet", fleet=self._fleet(straggler="yes"))
        )
        # v2 lines don't know the fleet kind at all
        assert schema.validate_line(
            {**self._line(kind="fleet", fleet=self._fleet()),
             "schema_version": 2}
        )

    def test_violations_detected(self):
        assert schema.validate_line("not a dict")
        assert any(
            "missing" in p
            for p in schema.validate_line({"schema_version": 1})
        )
        assert schema.validate_line(self._line(schema_version=99))
        assert schema.validate_line(self._line(kind="bogus"))
        assert schema.validate_line(self._line(step=-1))
        assert schema.validate_line(self._line(session_start_unix="soon"))
        assert schema.validate_line(self._line(counters={"c": -2}))
        assert schema.validate_line(self._line(counters={"c": 1.5}))
        assert schema.validate_line(self._line(metrics={"m": "oops"}))
        # exit_reason is required on final lines and forbidden elsewhere.
        assert schema.validate_line(self._line(kind="final"))
        assert not schema.validate_line(
            self._line(kind="final", exit_reason="complete")
        )
        assert schema.validate_line(self._line(exit_reason="complete"))
        with pytest.raises(ValueError, match="violates schema"):
            schema.validate(self._line(kind="bogus"))


# ------------------------------------------------- wired smoke run


@pytest.fixture(scope="class")
def smoke_run(tmp_path_factory):
    """One tiny MNIST fit with every telemetry surface on (acceptance
    criterion run): JSONL + trace + eval + checkpoints."""
    registry_mod.reset_default_registry()
    spans_mod.reset_default_tracer()
    wd = str(tmp_path_factory.mktemp("telemetry_smoke"))
    cfg = tiny_cfg(workdir=wd, eval_every=6)
    ds = _data()
    trainer = Trainer(mnist.make_task(cfg), cfg)
    metrics = trainer.fit(
        lambda start: train_iterator(ds, 64, seed=7, start_step=start),
        eval_iter_fn=lambda: eval_batches(_data(n=128), 64),
    )
    yield wd, cfg, trainer, metrics
    registry_mod.reset_default_registry()
    spans_mod.reset_default_tracer()


@pytest.mark.timeout(300)
class TestSmokeRun:
    def _lines(self, wd):
        with open(sinks_mod.metrics_path(wd)) as f:
            return [json.loads(line) for line in f]

    def test_every_jsonl_line_validates(self, smoke_run):
        wd, _, _, _ = smoke_run
        lines = self._lines(wd)
        assert lines, "no telemetry lines written"
        for line in lines:
            assert schema.validate_line(line) == [], line

    def test_window_cadence_and_final_marker(self, smoke_run):
        wd, cfg, _, _ = smoke_run
        lines = self._lines(wd)
        kinds = [(l["kind"], l["step"]) for l in lines]
        assert ("window", 4) in kinds and ("window", 12) in kinds
        assert lines[-1]["kind"] == "final"
        assert lines[-1]["exit_reason"] == "complete"
        assert lines[-1]["step"] == cfg.train_steps

    def test_counters_cover_wired_layers(self, smoke_run):
        wd, cfg, _, _ = smoke_run
        c = self._lines(wd)[-1]["counters"]
        assert c["train/steps_total"] == cfg.train_steps
        assert c["data/batches_fetched"] >= cfg.train_steps
        assert c["checkpoint/saves"] >= 2  # cadence + final
        assert c.get("data/batches_skipped", 0) == 0

    def test_derived_accounting_present(self, smoke_run):
        """The acceptance numbers: examples/sec, step-time p50/p95, MFU,
        goodput all non-null on window lines."""
        wd, _, _, _ = smoke_run
        windows = [l for l in self._lines(wd) if l["kind"] == "window"]
        for key in (
            "examples_per_sec",
            "step_time_p50",
            "step_time_p95",
            "mfu",
            "goodput",
        ):
            assert windows[-1]["derived"][key] is not None, key
        assert windows[-1]["derived"]["goodput"] == 1.0

    def test_trace_has_core_span_names(self, smoke_run):
        wd, _, _, _ = smoke_run
        with open(sinks_mod.trace_path(wd)) as f:
            trace = json.load(f)
        names = {e["name"] for e in trace["traceEvents"]}
        assert {
            "data_fetch",
            "device_step",
            "metric_flush",
            "checkpoint_save",
            "eval",
        } <= names, names

    def test_eval_line_emitted(self, smoke_run):
        wd, _, _, _ = smoke_run
        evals = [l for l in self._lines(wd) if l["kind"] == "eval"]
        assert evals and any(
            k.startswith("eval/") for k in evals[-1]["metrics"]
        )

    def test_schema_v3_memory_watermark(self, smoke_run):
        """ISSUE 3 acceptance (schema bumped to v3 by ISSUE 4): the run
        emits current-version lines with a nonzero peak-memory
        watermark, plus the fit-start breakdown snapshot attributing
        bytes to params vs. optimizer."""
        wd, _, _, _ = smoke_run
        lines = self._lines(wd)
        assert all(
            l["schema_version"] == schema.SCHEMA_VERSION for l in lines
        )
        mems = [l for l in lines if l["kind"] == "memory"]
        assert len(mems) == 1  # the fit-start snapshot
        bd = mems[0]["memory"]
        assert bd["params_bytes"] > 0
        assert bd["opt_bytes"] > 0  # adam moments embed the param tree
        assert bd["live_bytes"] >= bd["params_bytes"] + bd["opt_bytes"]
        windows = [l for l in lines if l["kind"] == "window"]
        assert windows[-1]["memory"]["peak_live_bytes"] > 0
        assert (
            lines[-1]["memory"]["peak_live_bytes"]
            >= lines[-1]["memory"]["live_bytes"]
        )

    def test_compile_counters_and_no_recompiles(self, smoke_run):
        """Fixed-shape training compiles each step fn exactly once
        (train + eval): the sentinel counts them, and no recompile
        warning fires."""
        wd, _, _, _ = smoke_run
        lines = self._lines(wd)
        c = lines[-1]["counters"]
        assert c["compile/count"] >= 2  # train_step + eval_step
        assert c.get("compile/recompiles", 0) == 0
        assert not [l for l in lines if l["kind"] == "compile_warning"]
        with open(sinks_mod.trace_path(wd)) as f:
            names = {e["name"] for e in json.load(f)["traceEvents"]}
        assert "compile" in names  # compile wall time is span-traced

    def test_fleet_lines_on_single_host(self, smoke_run):
        """ISSUE 4: even a one-host run emits a kind="fleet" line per
        cadenced window (one-host fleet, no straggler), every line
        carries the writing host index, and a window line precedes each
        fleet line at the same step."""
        wd, _, _, _ = smoke_run
        lines = self._lines(wd)
        assert all(l["host"] == 0 for l in lines)
        fleets = [l for l in lines if l["kind"] == "fleet"]
        windows = [l for l in lines if l["kind"] == "window"]
        assert len(fleets) == len(windows) >= 2
        assert [f["step"] for f in fleets] == [w["step"] for w in windows]
        fl = fleets[-1]["fleet"]
        assert [h["host"] for h in fl["hosts"]] == [0]
        assert fl["hosts"][0]["step_time_p95"] > 0
        assert fl["hosts"][0]["peak_live_bytes"] > 0
        assert fl["slowest_host"] == 0
        assert fl["skew"] == pytest.approx(1.0)
        assert fl["straggler"] is False

    def test_report_cli_on_real_run(self, smoke_run, capsys):
        """The full acceptance loop: the run dir feeds the report CLI,
        which must surface examples/sec, step-time p50/p95, the MFU
        estimate, and goodput. In-process main() — the subprocess-level
        contract is pinned in tests/test_tools.py."""
        import sys

        sys.path.insert(0, os.path.join(REPO, "tools"))
        import telemetry_report

        wd, _, _, _ = smoke_run
        rc = telemetry_report.main(
            [wd, "--json", os.path.join(wd, "report.json")]
        )
        out = capsys.readouterr().out
        assert rc == 0, out
        for needle in ("examples/sec", "p50", "p95", "mfu estimate",
                       "goodput", "ended: complete"):
            assert needle in out, (needle, out)
        rec = json.load(open(os.path.join(wd, "report.json")))
        for key in ("examples_per_sec_mean", "step_time_p50",
                    "step_time_p95", "mfu", "goodput"):
            assert rec[key] is not None, key
        assert rec["trace_phases"]["device_step"]["count"] > 0


# ------------------------------------------- abnormal-exit flushes


@pytest.mark.timeout(300)
class TestAbnormalExitFlush:
    """One Trainer (one jit compile) exercises both abnormal exit paths:
    the guard stays compiled-in ("skip" and "abort" share guard_on), and
    each fit rebinds workdir/policy via ``config.replace`` — fit() reads
    sinks, guard, and cadences from the live config at call time."""

    @pytest.fixture(scope="class")
    def exit_trainer(self):
        registry_mod.reset_default_registry()
        spans_mod.reset_default_tracer()
        cfg = tiny_cfg(
            train_steps=12, log_every=50, bad_step_policy="skip"
        )
        yield Trainer(mnist.make_task(cfg), cfg)
        registry_mod.reset_default_registry()
        spans_mod.reset_default_tracer()

    def test_sigterm_final_window_in_jsonl(
        self, faults, tmp_path, devices, exit_trainer, fresh_telemetry
    ):
        """Preemption satellite: the partial in-flight window must land
        in the JSONL before the clean exit — log_every is sized so NO
        cadenced window fires before the SIGTERM."""
        wd = str(tmp_path)
        trainer = exit_trainer
        trainer.config = trainer.config.replace(workdir=wd)
        ds = _data()
        faults("sigterm@4")
        with pytest.raises(resilience.Preempted):
            trainer.fit(
                lambda start: train_iterator(ds, 64, seed=7, start_step=start)
            )
        with open(sinks_mod.metrics_path(wd)) as f:
            lines = [json.loads(line) for line in f]
        assert lines, "preempt exit wrote no telemetry"
        final = lines[-1]
        assert schema.validate_line(final) == []
        assert final["kind"] == "final"
        assert final["exit_reason"] == "preempt"
        # The partial window's metrics made it out (steps 0..4 ran un-
        # logged), and the preemption itself is counted.
        assert any(k == "train/loss" for k in final["metrics"])
        assert final["counters"]["resilience/preemptions"] == 1
        assert final["counters"]["train/steps_total"] == final["step"]

    def test_bad_step_abort_writes_final_line(
        self, faults, tmp_path, devices, exit_trainer, fresh_telemetry
    ):
        wd = str(tmp_path)
        trainer = exit_trainer
        trainer.config = trainer.config.replace(
            workdir=wd, bad_step_policy="abort"
        )
        # The shared trainer resumed at step 5 (post-preemption state);
        # inject within the live step range.
        faults("nan@7")
        with pytest.raises(resilience.BadStepError):
            trainer.fit(train_iterator(_data(), 64, seed=0))
        with open(sinks_mod.metrics_path(wd)) as f:
            lines = [json.loads(line) for line in f]
        final = lines[-1]
        assert final["kind"] == "final"
        assert final["exit_reason"] == "error:BadStepError"
        assert final["counters"]["resilience/bad_steps"] >= 1
        assert accounting.goodput(final["counters"]) < 1.0


def test_emergency_flush_lands_fatal_marker(tmp_path, fresh_telemetry):
    """The watchdog-fatal hook (exit 87) must leave a final JSONL line
    and the trace on disk even when no window was ever emitted."""
    from tensorflow_examples_tpu.telemetry.hub import Telemetry

    reg, tracer = fresh_telemetry
    jsonl = str(tmp_path / "metrics.jsonl")
    trace = str(tmp_path / "trace.json")
    tel = Telemetry(
        [sinks_mod.JsonlSink(jsonl)], registry=reg, tracer=tracer,
        trace_file=trace,
    )
    # Counted AFTER creation: lines carry fit-start deltas.
    reg.counter("train/steps_total").inc(3)
    with tracer.span("device_step"):
        pass
    tel.emergency_flush()
    lines = [json.loads(l) for l in open(jsonl)]
    assert len(lines) == 1
    assert schema.validate_line(lines[0]) == []
    assert lines[0]["kind"] == "final"
    assert lines[0]["exit_reason"] == "watchdog_fatal"
    assert lines[0]["counters"]["train/steps_total"] == 3
    assert {e["name"] for e in json.load(open(trace))["traceEvents"]} == {
        "device_step"
    }


# ------------------------------------------------------ fleet monitor


class TestFleetMonitor:
    """telemetry/fleet.py unit layer: the mocked-allgather path (the
    real 2-process collective is pinned in tests/test_distributed.py)."""

    def _monitor(self, reg, allgather, *, skew_factor=2.0, count=2):
        from tensorflow_examples_tpu.telemetry import fleet as fleet_mod

        return fleet_mod.FleetMonitor(
            skew_factor=skew_factor, registry=reg, allgather=allgather,
            process_index=0, process_count=count,
        )

    def _feed(self, reg, *, step=0.01, fetch=0.001, n=10):
        for _ in range(n):
            reg.histogram("step_time").record(step)
            reg.histogram("span/data_fetch").record(fetch)
        reg.gauge("memory/peak_live_bytes").set(4096)

    def test_input_side_straggler_named(self, fresh_telemetry, caplog):
        """A host whose data-fetch excess explains its step-time excess
        is an INPUT-side straggler; the warning names host and side."""
        reg, _ = fresh_telemetry
        self._feed(reg)

        def allgather(vec):
            slow = vec.copy()
            slow[1] *= 5.0  # step_time_p95
            slow[2] += slow[1]  # the fetch IS the stall
            return np.stack([vec, slow])

        mon = self._monitor(reg, allgather)
        with caplog.at_level(
            logging.WARNING, logger="tensorflow_examples_tpu"
        ):
            summary = mon.gather({"resilience/steps_lost": 0})
        assert summary["slowest_host"] == 1
        assert summary["skew"] == pytest.approx(5.0, rel=1e-3)
        assert summary["side"] == "input"
        assert summary["straggler"] is True
        warned = [
            r.getMessage()
            for r in caplog.records
            if "FLEET STRAGGLER" in r.getMessage()
        ]
        assert len(warned) == 1
        assert "host 1" in warned[0] and "input-side" in warned[0]
        # one warning per straggling host per fit — a second window with
        # the same straggler stays quiet
        caplog.clear()
        with caplog.at_level(
            logging.WARNING, logger="tensorflow_examples_tpu"
        ):
            mon.gather({"resilience/steps_lost": 0})
        assert not [
            r for r in caplog.records
            if "FLEET STRAGGLER" in r.getMessage()
        ]

    def test_device_blocked_host_not_misreported_as_input_side(
        self, fresh_telemetry
    ):
        """ISSUE 6 satellite: input-side verdicts read data_work (host
        time PRODUCING batches), not data_fetch. A host whose fetch
        time is queue back-pressure wait — big data_fetch, small
        data_work — is compute-side; only real production time flips
        the verdict to input."""
        from tensorflow_examples_tpu.telemetry import fleet as fleet_mod

        reg, _ = fresh_telemetry
        self._feed(reg)
        for _ in range(10):
            reg.histogram("span/data_work").record(0.0005)
        work_i = fleet_mod.VECTOR_KEYS.index("data_work_p95")

        def blocked_on_device(vec):
            slow = vec.copy()
            slow[1] *= 5.0  # step time skewed...
            slow[2] += slow[1]  # ...and the FETCH span shows the wait
            # ...but data_work stays flat: the host wasn't producing.
            return np.stack([vec, slow])

        summary = self._monitor(reg, blocked_on_device).gather({})
        assert summary["slowest_host"] == 1
        assert summary["straggler"] is True
        assert summary["side"] == "compute"  # pre-fix: "input"

        def genuinely_input_bound(vec):
            slow = vec.copy()
            slow[1] *= 5.0
            slow[2] += slow[1]
            slow[work_i] += slow[1]  # the host really was producing
            return np.stack([vec, slow])

        summary = self._monitor(reg, genuinely_input_bound).gather({})
        assert summary["side"] == "input"
        # hosts entries carry the new key (numeric), schema-valid
        assert summary["hosts"][0]["data_work_p95"] is not None

    def test_compute_side_straggler(self, fresh_telemetry):
        """Skewed step time with flat data-fetch time = the device side
        (slow chip, thermal, busy host) is to blame."""
        reg, _ = fresh_telemetry
        self._feed(reg)

        def allgather(vec):
            slow = vec.copy()
            slow[1] *= 4.0  # step time skewed, fetch untouched
            return np.stack([vec, slow])

        summary = self._monitor(reg, allgather).gather({})
        assert summary["slowest_host"] == 1
        assert summary["side"] == "compute"
        assert summary["straggler"] is True

    def test_balanced_fleet_not_flagged(self, fresh_telemetry):
        reg, _ = fresh_telemetry
        self._feed(reg)

        def allgather(vec):
            other = vec.copy()
            other[1] *= 1.1  # 10% wobble is not a straggler
            return np.stack([vec, other])

        summary = self._monitor(reg, allgather).gather({})
        assert summary["straggler"] is False
        assert summary["skew"] == pytest.approx(1.1, rel=1e-3)

    def test_single_host_and_empty_registry(self, fresh_telemetry):
        reg, _ = fresh_telemetry
        mon = self._monitor(reg, None, count=1)
        # No samples at all: a valid summary with null attribution.
        empty = mon.gather({})
        assert empty["slowest_host"] is None
        assert empty["straggler"] is False
        self._feed(reg)
        summary = mon.gather({"resilience/steps_lost": 3})
        assert summary["hosts"][0]["steps_lost"] == 3
        assert summary["skew"] == pytest.approx(1.0)
        assert summary["straggler"] is False  # 1-host fleet never flags

    def test_emergency_snapshot_is_collective_free(self, fresh_telemetry):
        """The watchdog-fatal path must never enter a collective: the
        snapshot replays the cached summary (marked emergency), and
        works even before any gather happened."""
        reg, _ = fresh_telemetry
        self._feed(reg)
        calls = []

        def allgather(vec):
            calls.append(1)
            slow = vec.copy()
            slow[1] *= 5.0
            return np.stack([vec, slow])

        mon = self._monitor(reg, allgather)
        mon.gather({})
        assert len(calls) == 1
        snap = mon.snapshot()
        assert len(calls) == 1  # NO new collective
        assert snap["emergency"] is True
        assert snap["slowest_host"] == 1
        # Never gathered: local-only snapshot, still collective-free.
        cold = self._monitor(reg, allgather)
        snap = cold.snapshot()
        assert len(calls) == 1
        assert snap["emergency"] is True
        assert [h["host"] for h in snap["hosts"]] == [0]


@pytest.mark.timeout(300)
def test_fleet_line_names_fault_injected_straggler(
    tmp_path, faults, monkeypatch, fresh_telemetry, caplog
):
    """ISSUE 4 acceptance on CPU (mocked allgather): a run whose input
    pipeline is stalled by the ``slow`` fault spec must emit a fleet
    line naming THIS host as an input-side straggler, and log the
    warning naming host and side.

    Two fits: a healthy one whose measured health vector becomes the
    synthetic peer (host 1), then the fault-injected one as host 0 —
    the allgather mock stacks [this host, healthy peer], so the skew
    and side attribution come entirely from REAL measurements and the
    REAL injected fault, not from hand-written numbers.
    """
    from tensorflow_examples_tpu.telemetry import fleet as fleet_mod

    cfg = tiny_cfg(
        workdir=str(tmp_path), train_steps=8, log_every=4,
        checkpoint_every=0, straggler_skew_factor=2.0,
    )
    ds = _data()

    # ---- fit 1: healthy run; its vector is the synthetic fast peer ----
    trainer = Trainer(mnist.make_task(cfg), cfg)
    trainer.fit(lambda start: train_iterator(ds, 64, seed=7, start_step=start))
    healthy_vec = fleet_mod.FleetMonitor().local_vector({})
    assert np.isfinite(healthy_vec[:3]).all()

    # ---- fit 2: same trainer, slow-host fault armed, mocked fleet ----
    registry_mod.reset_default_registry()
    spans_mod.reset_default_tracer()

    def mock_allgather(vec):
        return np.stack([vec, healthy_vec])

    def from_config(cfg_):
        return fleet_mod.FleetMonitor(
            skew_factor=float(cfg_.straggler_skew_factor),
            allgather=mock_allgather,
            process_index=0,
            process_count=2,
        )

    monkeypatch.setattr(
        fleet_mod.FleetMonitor, "from_config", staticmethod(from_config)
    )
    faults("slow@5:1.0,slow@6:1.0")  # the injected slow host: host 0
    wd2 = str(tmp_path / "faulted")
    trainer.config = cfg.replace(workdir=wd2)
    with caplog.at_level(logging.WARNING, logger="tensorflow_examples_tpu"):
        # Fit 1 left the (checkpoint-less) state at step 8: continue to
        # 16 so this fit really steps; fetch indices restart at 0.
        trainer.fit(
            lambda start: train_iterator(ds, 64, seed=7, start_step=start),
            num_steps=16,
        )

    with open(sinks_mod.metrics_path(wd2)) as f:
        lines = [json.loads(line) for line in f]
    for line in lines:
        assert schema.validate_line(line) == [], line
    fleets = [l for l in lines if l["kind"] == "fleet"]
    assert fleets, [l["kind"] for l in lines]
    fl = fleets[-1]["fleet"]
    assert [h["host"] for h in fl["hosts"]] == [0, 1]
    assert fl["slowest_host"] == 0  # the fault-injected host, by name
    assert fl["straggler"] is True
    assert fl["side"] == "input"  # the stall sat in the data fetch
    assert fl["skew"] >= 2.0
    assert fl["hosts"][0]["data_fetch_p95"] >= 0.9  # the 1s stalls
    warned = [
        r.getMessage()
        for r in caplog.records
        if "FLEET STRAGGLER" in r.getMessage()
    ]
    assert warned and "host 0" in warned[0] and "input-side" in warned[0]


# ------------------------------------------------------ metrics server


def _get(url: str):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|NaN|[+-]Inf)$"
)


def _assert_valid_prometheus(text: str) -> list[str]:
    """Every line is a comment or a well-formed sample; returns the
    sample metric names."""
    names = []
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith(("# TYPE ", "# HELP ")), line
            continue
        assert _PROM_SAMPLE.match(line), f"invalid prometheus line: {line}"
        names.append(line.split("{")[0].split(" ")[0])
    return names


class TestMetricsServer:
    def test_endpoints_serve_registry_and_window(self, fresh_telemetry):
        import json as json_mod

        from tensorflow_examples_tpu.telemetry import fleet as fleet_mod
        from tensorflow_examples_tpu.telemetry import serve as serve_mod
        from tensorflow_examples_tpu.telemetry.hub import Telemetry

        reg, tracer = fresh_telemetry
        reg.counter("train/steps_total").inc(7)
        reg.gauge("memory/peak_live_bytes").set(2048)
        reg.histogram("step_time").record(0.01)
        tel = Telemetry(
            [], registry=reg, tracer=tracer, host=0,
            fleet=fleet_mod.FleetMonitor(
                registry=reg, process_index=0, process_count=1
            ),
        )
        srv = serve_mod.MetricsServer(reg, port=0, telemetry=tel).start()
        try:
            # /window and /fleet 404 before any line exists
            status, _ = _get(srv.url("/window"))
            assert status == 404
            status, _ = _get(srv.url("/fleet"))
            assert status == 404
            # the fit-start memory snapshot must NOT satisfy /window —
            # its contract is the latest window/eval/final line
            tel.log_window(
                0, {}, kind="memory", reduce=False,
                extra={"memory": {"live_bytes": 1, "params_bytes": 1}},
            )
            status, _ = _get(srv.url("/window"))
            assert status == 404
            tel.log_window(7, {"loss": 1.25})
            status, text = _get(srv.url("/metrics"))
            assert status == 200
            names = _assert_valid_prometheus(text)
            assert "train_steps_total" in names
            assert "memory_peak_live_bytes" in names
            assert "step_time_seconds_count" in names
            assert 'host="0"' in text
            status, body = _get(srv.url("/health"))
            assert status == 200
            health = json_mod.loads(body)
            assert health["ok"] is True
            assert health["last_step"] == 7
            assert health["last_window_age_secs"] < 60
            # /window serves the WINDOW line (metrics intact), even
            # though the fleet line was emitted after it; /fleet serves
            # the fleet summary.
            status, body = _get(srv.url("/window"))
            assert status == 200
            line = json_mod.loads(body)
            assert line["kind"] == "window"
            assert line["step"] == 7
            assert line["metrics"]["train/loss"] == 1.25
            status, body = _get(srv.url("/fleet"))
            assert status == 200
            fleet_line = json_mod.loads(body)
            assert fleet_line["kind"] == "fleet"
            assert fleet_line["fleet"]["hosts"][0]["host"] == 0
            status, _ = _get(srv.url("/bogus"))
            assert status == 404
        finally:
            srv.close()
        srv.close()  # idempotent

    def test_health_503_on_watchdog_stall(self, fresh_telemetry):
        import json as json_mod
        import time as time_mod

        from tensorflow_examples_tpu.telemetry import serve as serve_mod
        from tensorflow_examples_tpu.utils.diagnostics import Watchdog

        reg, _ = fresh_telemetry
        wd = Watchdog(0.05, poll_s=10.0)  # not started: no dump thread
        wd.enter("device_step")
        srv = serve_mod.MetricsServer(reg, port=0, watchdog=wd).start()
        try:
            time_mod.sleep(0.1)  # stall past the timeout
            status, body = _get(srv.url("/health"))
            assert status == 503
            health = json_mod.loads(body)
            assert health["ok"] is False
            assert health["phase"] == "device_step"
            assert health["stalled_secs"] >= 0.05
            wd.pause()  # paused phases (eval, ckpt) are not stalls
            status, _ = _get(srv.url("/health"))
            assert status == 200
        finally:
            srv.close()

    def test_from_config_gating(self, fresh_telemetry):
        from tensorflow_examples_tpu.telemetry import serve as serve_mod

        assert serve_mod.MetricsServer.from_config(tiny_cfg()) is None
        srv = serve_mod.MetricsServer.from_config(
            tiny_cfg(metrics_port=18347)
        )
        assert srv is not None and srv.requested_port == 18347

    def test_sanitize_and_render(self, fresh_telemetry):
        from tensorflow_examples_tpu.telemetry import serve as serve_mod

        assert serve_mod.sanitize_metric_name("a/b-c.d") == "a_b_c_d"
        assert serve_mod.sanitize_metric_name("0weird") == "_0weird"
        reg, _ = fresh_telemetry
        reg.counter("io/retries").inc(2)
        text = serve_mod.render_prometheus(reg, host=3)
        assert "# TYPE io_retries counter" in text
        assert 'io_retries{host="3"} 2.0' in text


@pytest.mark.timeout(300)
def test_metrics_served_during_live_fit(tmp_path, fresh_telemetry):
    """ISSUE 4 acceptance: with metrics_port set, /metrics serves valid
    Prometheus text and /health answers WHILE the run is live (queried
    from inside the input pipeline, mid-fit), and the port is closed on
    the fit exit path."""
    import socket
    import urllib.error
    import urllib.request

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = tiny_cfg(
        workdir=str(tmp_path), metrics_port=port, train_steps=8,
        log_every=4, checkpoint_every=0, watchdog_secs=30,
    )
    ds = _data()
    captured = {}

    def data(start):
        for i, batch in enumerate(
            train_iterator(ds, 64, seed=7, start_step=start)
        ):
            if i == 6 and not captured:  # after the step-4 window landed
                captured["metrics"] = _get(f"http://127.0.0.1:{port}/metrics")
                captured["health"] = _get(f"http://127.0.0.1:{port}/health")
                captured["window"] = _get(f"http://127.0.0.1:{port}/window")
            yield batch

    trainer = Trainer(mnist.make_task(cfg), cfg)
    trainer.fit(data)
    assert captured, "input pipeline never reached the probe batch"
    status, text = captured["metrics"]
    assert status == 200
    names = _assert_valid_prometheus(text)
    assert "train_steps_total" in names
    status, body = captured["health"]
    assert status == 200
    health = json.loads(body)
    assert health["ok"] is True and health["phase"] is not None
    status, body = captured["window"]
    assert status == 200
    assert json.loads(body)["step"] == 4
    # Exit path closed the server: the port no longer answers.
    assert trainer._telemetry.server is None
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=2)


def test_emergency_flush_fleet_snapshot_and_server_close(
    tmp_path, fresh_telemetry
):
    """ISSUE 4 satellite: the watchdog-fatal hook lands the cached
    fleet state as an emergency kind="fleet" line and closes the
    metrics server — before the final marker hits the disk is fine,
    before exit 87 is the contract."""
    import urllib.error
    import urllib.request

    from tensorflow_examples_tpu.telemetry import fleet as fleet_mod
    from tensorflow_examples_tpu.telemetry import serve as serve_mod
    from tensorflow_examples_tpu.telemetry.hub import Telemetry

    reg, tracer = fresh_telemetry
    reg.histogram("step_time").record(0.01)
    jsonl = str(tmp_path / "metrics.jsonl")
    mon = fleet_mod.FleetMonitor(
        skew_factor=2.0, registry=reg, process_index=0, process_count=1
    )
    tel = Telemetry(
        [sinks_mod.JsonlSink(jsonl)], registry=reg, tracer=tracer,
        fleet=mon, host=0,
    )
    srv = serve_mod.MetricsServer(reg, port=0, telemetry=tel).start()
    tel.server = srv
    port = srv.port
    tel.emergency_flush()
    lines = [json.loads(l) for l in open(jsonl)]
    # window-less run: [fleet snapshot, final marker], both schema-valid
    assert [l["kind"] for l in lines[-2:]] == ["fleet", "final"]
    for line in lines:
        assert schema.validate_line(line) == [], line
    assert lines[-2]["fleet"]["emergency"] is True
    assert lines[-1]["exit_reason"] == "watchdog_fatal"
    assert tel.server is None
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=2)


class TestTensorBoardSinkFallback:
    def test_null_writer_warns_once_naming_cause(
        self, tmp_path, caplog, monkeypatch
    ):
        """_make_writer satellite: the old silent `except: return None`
        becomes an explicit null writer + ONE warning naming the import
        failure."""
        import sys

        monkeypatch.setitem(sys.modules, "clu", None)  # import -> error
        monkeypatch.setattr(sinks_mod, "_tb_warned", False)
        with caplog.at_level(
            logging.WARNING, logger="tensorflow_examples_tpu"
        ):
            sink = sinks_mod.TensorBoardSink(str(tmp_path))
        warned = [
            r
            for r in caplog.records
            if "TensorBoard sink unavailable" in r.getMessage()
        ]
        assert len(warned) == 1
        # Names the failure class and its message (ModuleNotFoundError
        # here, via the sys.modules[...] = None import block).
        assert "Error" in warned[0].getMessage()
        assert "clu" in warned[0].getMessage()
        # Null behavior: writes are inert, never raising.
        sink.write(
            {"step": 1, "metrics": {"train/loss": 1.0}, "derived": {}}
        )
        sink.flush()
        caplog.clear()
        with caplog.at_level(
            logging.WARNING, logger="tensorflow_examples_tpu"
        ):
            sinks_mod.TensorBoardSink(str(tmp_path))  # second: quiet
        assert not [
            r
            for r in caplog.records
            if "TensorBoard sink unavailable" in r.getMessage()
        ]

    def test_unknown_sink_name_rejected(self):
        with pytest.raises(ValueError, match="unknown telemetry sink"):
            sinks_mod.make_sinks("jsonl,frobnicator", "")


# ------------------------------------------------------- watchdog span


def test_watchdog_dump_names_open_span(caplog, fresh_telemetry):
    import time

    from tensorflow_examples_tpu.utils.diagnostics import Watchdog

    hangs = []
    wd = Watchdog(
        0.15, on_hang=lambda step, stalled: hangs.append(step), poll_s=0.03
    ).start()
    try:
        wd.ping(3)
        with caplog.at_level(
            logging.ERROR, logger="tensorflow_examples_tpu"
        ):
            with spans_mod.span("data_fetch"):
                time.sleep(0.4)
    finally:
        wd.stop()
    dumps = [
        r.getMessage() for r in caplog.records if "WATCHDOG" in r.getMessage()
    ]
    assert dumps and "data_fetch" in dumps[0]


# ----------------------------------------- recompilation sentinel


class TestCompilationSentinel:
    def test_signature_and_delta_name_changed_axis(self):
        from tensorflow_examples_tpu.telemetry import compilation

        a = compilation.abstract_signature(
            ({"x": np.zeros((64, 28), np.float32)},), {}
        )
        b = compilation.abstract_signature(
            ({"x": np.zeros((32, 28), np.float32)},), {}
        )
        assert a != b
        delta = compilation.describe_delta(a, b)
        assert "axis 0: 64->32" in delta and "'x'" in delta
        # dtype changes are named too
        c = compilation.abstract_signature(
            ({"x": np.zeros((32, 28), np.float16)},), {}
        )
        assert "dtype float32->float16" in compilation.describe_delta(b, c)
        assert compilation.describe_delta(None, a) == "first compilation"

    def test_wrapper_counts_and_warns_after_warmup(self, fresh_telemetry):
        from tensorflow_examples_tpu.telemetry import compilation

        reg, _ = fresh_telemetry
        sentinel = compilation.CompilationSentinel(warmup=1)
        calls = []
        wrapped = sentinel.wrap(lambda x: calls.append(1) or x, "f")
        events = []
        sentinel.on_recompile = events.append
        x64, x32 = np.zeros((64,)), np.zeros((32,))
        wrapped(x64)
        wrapped(x64)  # cached signature: no new compile
        assert reg.counter("compile/count").value == 1
        assert not events
        sentinel.step = 7
        wrapped(x32)  # post-warmup recompile
        assert reg.counter("compile/count").value == 2
        assert reg.counter("compile/recompiles").value == 1
        assert len(events) == 1
        assert events[0]["step"] == 7 and events[0]["fn"] == "f"
        assert "axis 0: 64->32" in events[0]["delta"]
        wrapped(x32)  # now-known signature: quiet
        assert len(events) == 1
        assert len(calls) == 4  # every call reached the wrapped fn

    def test_wrapper_forwards_attributes(self):
        from tensorflow_examples_tpu.telemetry import compilation

        sentinel = compilation.CompilationSentinel()
        jitted = jax.jit(lambda x: x * 2)
        wrapped = sentinel.wrap(jitted, "g")
        # The AOT surface bench.py and the diag tools rely on:
        lowered = wrapped.lower(np.ones((4,), np.float32))
        assert lowered.compile() is not None
        assert sentinel.wrap(None, "absent") is None

    @pytest.mark.timeout(300)
    def test_post_warmup_shape_change_emits_one_warning_line(
        self, tmp_path, devices, fresh_telemetry
    ):
        """ISSUE 3 acceptance, one fit covering both device-side paths:
        a post-warmup batch-shape change triggers EXACTLY ONE
        compile_warning JSONL line naming the changed axis (the
        repeated new shape is then a known signature), while an in-loop
        profiler window ([2, 5)) captures a real device trace
        cross-linked from the final line."""
        import glob

        wd = str(tmp_path)
        cfg = tiny_cfg(
            workdir=wd, train_steps=8, log_every=4, checkpoint_every=0,
            eval_every=0, profile_start_step=2, profile_num_steps=3,
        )
        ds = _data()

        def data(start):
            base = train_iterator(ds, 64, seed=7, start_step=start)
            for i, batch in enumerate(base):
                if i + start >= 5:  # ragged from step 5 on
                    batch = {k: v[:32] for k, v in batch.items()}
                yield batch

        trainer = Trainer(mnist.make_task(cfg), cfg)
        trainer.fit(data)
        with open(sinks_mod.metrics_path(wd)) as f:
            lines = [json.loads(line) for line in f]
        warnings = [l for l in lines if l["kind"] == "compile_warning"]
        assert len(warnings) == 1, [l["kind"] for l in lines]
        line = warnings[0]
        assert schema.validate_line(line) == []
        comp = line["compile"]
        assert comp["fn"] == "train_step"
        assert "axis 0: 64->32" in comp["delta"]
        assert "'image'" in comp["delta"]
        final = lines[-1]
        assert final["counters"]["compile/count"] == 2
        assert final["counters"]["compile/recompiles"] == 1

        # ---- the profiler window, from the same run ----
        assert schema.validate_line(final) == []
        prof = final["profile"]
        assert prof["start_step"] == 2
        assert prof["num_steps"] == 3
        assert prof["dir"] == os.path.join(wd, "profile")
        assert prof["wall_secs"] > 0
        assert final["gauges"]["profile/steps"] == 3
        assert glob.glob(
            os.path.join(wd, "profile", "**", "*.xplane.pb"),
            recursive=True,
        ), "profiler window captured no device trace"
        with open(sinks_mod.trace_path(wd)) as f:
            names = {e["name"] for e in json.load(f)["traceEvents"]}
        assert "profile" in names  # the bracketing span


# ------------------------------------------------ memory accounting


class TestMemoryAccounting:
    def test_tree_bytes_concrete_and_abstract(self):
        import jax.numpy as jnp

        from tensorflow_examples_tpu.telemetry import memory as memory_mod

        tree = {
            "a": jnp.ones((4, 4), jnp.float32),
            "b": jnp.ones((2,), jnp.int32),
        }
        assert memory_mod.tree_bytes(tree) == 64 + 8
        abstract = jax.eval_shape(lambda: tree)
        assert memory_mod.tree_bytes(abstract) == 64 + 8

    def test_state_byte_breakdown(self):
        import jax.numpy as jnp
        import optax

        from tensorflow_examples_tpu.train.state import TrainState

        state = TrainState.create(
            apply_fn=None,
            params={"w": jnp.ones((10,), jnp.float32)},
            tx=optax.adam(1e-3),
        )
        sizes = state.byte_breakdown()
        assert sizes["params"] == 40
        assert sizes["opt_state"] >= 80  # adam mu + nu embed the params
        assert sizes["model_state"] == 0

    def test_is_oom_classification(self):
        from tensorflow_examples_tpu.telemetry import memory as memory_mod

        assert memory_mod.is_oom(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                         "1073741824 bytes")
        )
        assert memory_mod.is_oom(ValueError("allocation failure"))
        assert memory_mod.is_oom(RuntimeError("OOM when allocating"))
        assert not memory_mod.is_oom(ValueError("shape mismatch"))
        assert not memory_mod.is_oom(RuntimeError("in the classroom"))

    def test_monitor_watermark_and_forensics(self, fresh_telemetry):
        import jax.numpy as jnp

        from tensorflow_examples_tpu.telemetry import memory as memory_mod

        reg, _ = fresh_telemetry
        mon = memory_mod.MemoryMonitor(registry=reg)
        big = jnp.ones((256, 256), jnp.float32)  # 256 KiB resident
        live = mon.sample()
        assert live >= big.nbytes
        assert reg.gauge("memory/peak_live_bytes").value == live
        fields = mon.window_fields()
        assert fields["peak_live_bytes"] >= fields["live_bytes"] - 1
        report = mon.oom_report(top=3)
        assert "live arrays" in report and "MiB" in report
        assert "(256, 256)" in report  # the big array is named
        del big

    def test_oom_teardown_hook_logs_report(self, caplog, fresh_telemetry):
        from tensorflow_examples_tpu.telemetry import memory as memory_mod

        mon = memory_mod.MemoryMonitor()
        with caplog.at_level(
            logging.ERROR, logger="tensorflow_examples_tpu"
        ):
            assert memory_mod.maybe_log_oom_report(
                RuntimeError("RESOURCE_EXHAUSTED: out of memory"), mon
            )
            assert not memory_mod.maybe_log_oom_report(
                ValueError("unrelated"), mon
            )
            assert not memory_mod.maybe_log_oom_report(None, mon)
        dumps = [
            r.getMessage()
            for r in caplog.records
            if "OOM allocation forensics" in r.getMessage()
        ]
        assert len(dumps) == 1


# ------------------------------------------------- profiler windows


class TestProfilerWindow:
    def test_from_config_mappings(self):
        from tensorflow_examples_tpu.telemetry import profiling

        assert profiling.ProfilerWindow.from_config(tiny_cfg()) is None
        legacy = profiling.ProfilerWindow.from_config(
            tiny_cfg(profile=True)
        )
        assert (legacy.start_step, legacy.num_steps) == (10, 10)
        explicit = profiling.ProfilerWindow.from_config(
            tiny_cfg(profile_start_step=3, profile_num_steps=5,
                     workdir="/w")
        )
        assert (explicit.start_step, explicit.num_steps) == (3, 5)
        assert explicit.out_dir == os.path.join("/w", "profile")
        override = profiling.ProfilerWindow.from_config(
            tiny_cfg(profile_num_steps=2, profile_dir="/elsewhere")
        )
        assert override.out_dir == "/elsewhere"
        # The wired capture (real trace + final-line cross-link) is
        # asserted on the sentinel acceptance fit above — one shared
        # training run keeps the tier-1 budget flat.


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
