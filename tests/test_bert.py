"""BERT model + GLUE workload: HF parity, metric math, e2e fine-tune."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflow_examples_tpu.models import bert
from tensorflow_examples_tpu.ops import glue_metrics
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.data.memory import eval_batches, train_iterator
from tensorflow_examples_tpu.workloads import bert_glue


def tiny_cfg(**kw):
    base = dict(
        task="sst2",
        seq_len=16,
        vocab_size=120,
        num_layers=2,
        num_heads=2,
        d_model=16,
        d_ff=32,
        dropout=0.0,
        global_batch_size=16,
        train_steps=40,
        warmup_steps=4,
        learning_rate=3e-4,
        log_every=20,
        eval_every=0,
        checkpoint_every=0,
        precision="f32",
    )
    base.update(kw)
    return bert_glue.BertGlueConfig(**base)


def run_tiny(cfg, mesh):
    task = bert_glue.make_task(cfg, mesh=mesh)
    trainer = Trainer(task, cfg, mesh=mesh)
    train_ds, _ = bert_glue.datasets(cfg)
    it = train_iterator(train_ds, cfg.global_batch_size, seed=0)
    losses = []
    state = trainer.state
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        losses.append(float(m["loss"]))
    trainer.state = state
    return losses, trainer


def test_padding_mask_invariance():
    """Tokens beyond attention_mask must not affect the logits."""
    cfg = bert.BertConfig(
        vocab_size=50, max_len=16, num_layers=2, num_heads=2,
        d_model=16, d_ff=32, dropout=0.0,
    )
    model = bert.BertClassifier(cfg, num_labels=2)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(1, 50, (2, 16)), jnp.int32)
    mask = jnp.asarray((np.arange(16) < 10)[None].repeat(2, 0), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    out1 = model.apply({"params": params}, tokens, mask)
    toks2 = tokens.at[:, 12].set(7)
    out2 = model.apply({"params": params}, toks2, mask)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)


def test_flash_attention_matches_xla():
    """attention="flash" (Pallas kernel + key-bias padding mask) must
    reproduce the XLA softmax path on ragged per-row masks — logits AND
    parameter gradients."""
    base = dict(
        vocab_size=50, max_len=32, num_layers=2, num_heads=2,
        d_model=16, d_ff=32, dropout=0.0,
    )
    model_x = bert.BertClassifier(bert.BertConfig(**base), num_labels=2)
    model_f = bert.BertClassifier(
        bert.BertConfig(**base, attention="flash"), num_labels=2
    )
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(1, 50, (3, 32)), jnp.int32)
    lengths = np.asarray([32, 20, 7])
    mask = jnp.asarray(
        (np.arange(32)[None] < lengths[:, None]).astype(np.int32)
    )
    params = model_x.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    out_x = model_x.apply({"params": params}, tokens, mask)
    out_f = model_f.apply({"params": params}, tokens, mask)
    np.testing.assert_allclose(
        np.asarray(out_x), np.asarray(out_f), atol=2e-4, rtol=2e-4
    )

    def loss(m):
        return lambda p: jnp.sum(m.apply({"params": p}, tokens, mask) ** 2)

    g_x = jax.grad(loss(model_x))(params)
    g_f = jax.grad(loss(model_f))(params)
    for a, b in zip(jax.tree.leaves(g_x), jax.tree.leaves(g_f)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4
        )


def test_flash_attention_under_tp_mesh_matches_xla():
    """attention="flash" on a dp×model mesh (ADVICE r3): the key-bias
    flash call now rides the mesh-aware shard_map wrapper, so heads
    stay sharded over `model` around the Pallas call — logits must
    still match the XLA softmax path on ragged masks, and the compiled
    step must not all-gather heads around the kernel."""
    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh

    base = dict(
        vocab_size=50, max_len=32, num_layers=2, num_heads=4,
        d_model=16, d_ff=32, dropout=0.0,
    )
    mesh = create_mesh(MeshConfig(data=2, model=4))
    model_x = bert.BertClassifier(bert.BertConfig(**base), num_labels=2)
    model_f = bert.BertClassifier(
        bert.BertConfig(**base, attention="flash"), num_labels=2, mesh=mesh
    )
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(1, 50, (4, 32)), jnp.int32)
    lengths = np.asarray([32, 20, 7, 13])
    mask = jnp.asarray(
        (np.arange(32)[None] < lengths[:, None]).astype(np.int32)
    )
    params = model_x.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    out_x = model_x.apply({"params": params}, tokens, mask)
    fwd = jax.jit(lambda p, t, m: model_f.apply({"params": p}, t, m))
    with mesh:
        out_f = fwd(params, tokens, mask)
        hlo = fwd.lower(params, tokens, mask).compile().as_text()
    np.testing.assert_allclose(
        np.asarray(out_x), np.asarray(out_f), atol=2e-4, rtol=2e-4
    )
    # The no-gather property itself: the compiled forward's only
    # collectives are the Megatron row-parallel psums — zero all-gather
    # instruction DEFINITIONS (operand references like %all-gather.1
    # don't match the definition regex).
    import re

    defs = re.findall(
        r"^\s*(?:ROOT )?%?[\w.\-]+ = (?:.+?) (all-gather|all-to-all)"
        r"(?:-start)?\(",
        hlo,
        re.M,
    )
    assert not defs, f"unexpected gathers around the flash call: {defs}"


def test_hf_parity():
    """Imported HF BertForSequenceClassification weights → identical logits."""
    torch = pytest.importorskip("torch")
    from transformers import BertConfig as HFBertConfig
    from transformers import BertForSequenceClassification

    from tensorflow_examples_tpu.models.hf_import import import_bert

    hf_cfg = HFBertConfig(
        vocab_size=120, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=32,
        max_position_embeddings=32, num_labels=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        classifier_dropout=0.0,
    )
    torch.manual_seed(0)
    hf_model = BertForSequenceClassification(hf_cfg).eval()
    cfg, params = import_bert(hf_model)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 120, (2, 12))
    mask = np.ones((2, 12), np.int64)
    mask[1, 8:] = 0
    type_ids = np.zeros((2, 12), np.int64)
    type_ids[:, 6:] = 1
    with torch.no_grad():
        hf_logits = hf_model(
            torch.tensor(tokens),
            attention_mask=torch.tensor(mask),
            token_type_ids=torch.tensor(type_ids),
        ).logits.numpy()

    model = bert.BertClassifier(cfg, num_labels=2)
    ours = model.apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        jnp.asarray(tokens, jnp.int32),
        jnp.asarray(mask, jnp.int32),
        jnp.asarray(type_ids, jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=2e-4)


def test_glue_metric_math():
    """F1/MCC/Pearson from aggregated rates must match direct formulas."""
    rng = np.random.default_rng(0)
    preds = rng.integers(0, 2, 200)
    labels = rng.integers(0, 2, 200)
    m = {
        k: float(v)
        for k, v in glue_metrics.confusion_rates(
            jnp.asarray(preds), jnp.asarray(labels), None
        ).items()
    }
    tp = np.sum((preds == 1) & (labels == 1))
    fp = np.sum((preds == 1) & (labels == 0))
    fn = np.sum((preds == 0) & (labels == 1))
    tn = np.sum((preds == 0) & (labels == 0))
    f1_direct = 2 * tp / (2 * tp + fp + fn)
    mcc_direct = (tp * tn - fp * fn) / np.sqrt(
        (tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)
    )
    assert abs(glue_metrics.f1_from_rates(m) - f1_direct) < 1e-6
    assert abs(glue_metrics.mcc_from_rates(m) - mcc_direct) < 1e-6

    x = rng.normal(0, 1, 300)
    y = 0.7 * x + rng.normal(0, 0.5, 300)
    mm = {
        k: float(v)
        for k, v in glue_metrics.moment_means(
            jnp.asarray(x), jnp.asarray(y), None
        ).items()
    }
    assert abs(
        glue_metrics.pearson_from_moments(mm) - np.corrcoef(x, y)[0, 1]
    ) < 1e-5


def test_finetune_learns_sst2(mesh8):
    cfg = tiny_cfg()
    losses, trainer = run_tiny(cfg, mesh8)
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1
    eval_ds = bert_glue.eval_dataset(cfg)
    metrics = trainer.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    assert metrics["accuracy"] > 0.6  # planted-marker task is learnable
    assert "tp" not in metrics  # finalize strips raw rates


def test_stsb_regression(mesh8):
    cfg = tiny_cfg(task="stsb", train_steps=30)
    losses, trainer = run_tiny(cfg, mesh8)
    assert np.all(np.isfinite(losses))
    eval_ds = bert_glue.eval_dataset(cfg)
    metrics = trainer.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    assert "pearson" in metrics and -1.0 <= metrics["pearson"] <= 1.0


def test_cola_mcc(mesh8):
    cfg = tiny_cfg(task="cola", train_steps=10)
    _, trainer = run_tiny(cfg, mesh8)
    eval_ds = bert_glue.eval_dataset(cfg)
    metrics = trainer.evaluate(eval_batches(eval_ds, cfg.global_batch_size))
    assert "mcc" in metrics and -1.0 <= metrics["mcc"] <= 1.0


def test_glue_text_to_finetune_chain(tmp_path, mesh8):
    """The full text path (VERDICT r1 item 4): raw GLUE TSV →
    tools/prepare_glue.py (in-repo WordPiece, vocab built from the task
    text) → <task>_<split>.npz → bert_glue workload fine-tune learns the
    separable toy labels through the shared Trainer."""
    import subprocess
    import sys
    import os

    tsv = tmp_path / "train.tsv"
    rows = ["sentence\tlabel"]
    for i in range(64):
        text = "a wonderful heartfelt triumph" if i % 2 else "a dreary boring failure"
        rows.append(f"{text} number {i}\t{i % 2}")
    tsv.write_text("\n".join(rows) + "\n")
    out = tmp_path / "glue"
    tool = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools",
        "prepare_glue.py",
    )
    r = subprocess.run(
        [
            sys.executable, tool, "--task=sst2", f"--input={tsv}",
            "--split=train", f"--out_dir={out}", "--build_vocab=160",
            "--seq_len=16",
        ],
        capture_output=True,
        text=True,
        # CPU-only tool: the sitecustomize axon register() can block
        # interpreter start >=90 s while the tunnel is wedged.
        env={k: v for k, v in os.environ.items()
             if k != "PALLAS_AXON_POOL_IPS"},
    )
    assert r.returncode == 0, r.stderr

    cfg = tiny_cfg(
        data_dir=str(out), vocab_size=160, train_steps=30, learning_rate=1e-3
    )
    losses, trainer = run_tiny(cfg, mesh8)
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1, losses
    # Eval on the train-split features (no val file): accuracy ≈ 1 on
    # the separable toy task proves the features carry the signal.
    from tensorflow_examples_tpu.data.sources import load_glue

    ds = load_glue(str(out), "sst2", "train", seq_len=16, vocab_size=160)
    m = trainer.evaluate(eval_batches(ds, cfg.global_batch_size))
    assert m["accuracy"] > 0.9, m
