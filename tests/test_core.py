"""Core layer tests: mesh construction, sharding rules, precision, rng."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tensorflow_examples_tpu.core.mesh import (
    AxisNames,
    MeshConfig,
    create_mesh,
    local_batch_size,
)
from tensorflow_examples_tpu.core.precision import PrecisionPolicy
from tensorflow_examples_tpu.core.sharding import (
    ShardingRules,
    shard_params,
    shardings_for_params,
)


class TestMesh:
    def test_default_mesh_all_data(self, devices):
        mesh = create_mesh()
        assert mesh.shape[AxisNames.DATA] == 8
        assert mesh.shape[AxisNames.MODEL] == 1

    def test_mixed_mesh(self, devices):
        mesh = create_mesh(MeshConfig(data=2, model=2, context=2))
        assert dict(mesh.shape) == {
            "data": 2, "fsdp": 1, "model": 2, "context": 2, "pipe": 1,
        }

    def test_bad_mesh_raises(self, devices):
        with pytest.raises(ValueError):
            create_mesh(MeshConfig(data=3, model=2))

    def test_local_batch(self, mesh8):
        assert local_batch_size(64, mesh8) == 64  # single process

    def test_indivisible_batch_raises(self, mesh8):
        with pytest.raises(ValueError):
            local_batch_size(63, mesh8)


class TestShardingRules:
    def test_first_match_wins_and_default_replicates(self):
        rules = ShardingRules(
            [
                (r"attn/kernel$", P(None, "model")),
                (r"kernel$", P("fsdp", None)),
            ]
        )
        assert rules.spec_for("h_0/attn/kernel") == P(None, "model")
        assert rules.spec_for("h_0/mlp/kernel") == P("fsdp", None)
        assert rules.spec_for("h_0/bias") == P()

    def test_size_one_axes_dropped(self, mesh8):
        # model axis has size 1 on a data-only mesh → spec must drop it.
        rules = ShardingRules([(r"w", P("data", "model"))])
        params = {"w": jnp.zeros((16, 4))}
        sh = shardings_for_params(params, mesh8, rules)
        assert sh["w"].spec == P("data", None)

    def test_shard_params_places_data(self, mesh8):
        rules = ShardingRules([(r"w", P("data"))])
        params = {"w": jnp.arange(16.0).reshape(16, 1), "b": jnp.zeros((3,))}
        out = shard_params(params, mesh8, rules)
        assert out["w"].sharding.spec == P("data")
        np.testing.assert_allclose(out["w"], params["w"])
        # b unmatched → replicated
        assert out["b"].sharding.spec == P()


class TestPrecision:
    def test_policies(self):
        p = PrecisionPolicy.create("bf16")
        assert p.param_dtype == jnp.float32
        assert p.compute_dtype == jnp.bfloat16

    def test_cast_skips_ints(self):
        p = PrecisionPolicy.create("bf16")
        tree = {"w": jnp.zeros((2,), jnp.float32), "i": jnp.zeros((2,), jnp.int32)}
        out = p.cast_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["i"].dtype == jnp.int32


class TestRng:
    def test_step_keys_differ_and_reproduce(self):
        from tensorflow_examples_tpu.core.rng import named_rngs, step_rng

        key = jax.random.PRNGKey(0)
        a = named_rngs(step_rng(key, jnp.int32(3)))
        b = named_rngs(step_rng(key, jnp.int32(4)))
        a2 = named_rngs(step_rng(key, jnp.int32(3)))
        assert not np.array_equal(a["dropout"], b["dropout"])
        np.testing.assert_array_equal(a["dropout"], a2["dropout"])


class TestDiagnostics:
    def test_watchdog_fires_on_hang(self):
        import threading

        from tensorflow_examples_tpu.utils.diagnostics import Watchdog

        fired = threading.Event()
        wd = Watchdog(
            timeout_s=0.2,
            on_hang=lambda step, stalled: fired.set(),
            poll_s=0.05,
        ).start()
        try:
            wd.ping(0)
            assert fired.wait(timeout=2.0), "watchdog did not fire on hang"
        finally:
            wd.stop()

    def test_watchdog_quiet_when_pinged(self):
        import threading
        import time

        from tensorflow_examples_tpu.utils.diagnostics import Watchdog

        fired = threading.Event()
        wd = Watchdog(
            timeout_s=0.5,
            on_hang=lambda step, stalled: fired.set(),
            poll_s=0.05,
        ).start()
        try:
            for i in range(10):
                wd.ping(i)
                time.sleep(0.05)
            assert not fired.is_set()
        finally:
            wd.stop()

    def test_install_crash_handlers(self, tmp_path):
        import os

        from tensorflow_examples_tpu.utils.diagnostics import (
            install_crash_handlers,
        )

        install_crash_handlers(str(tmp_path))
        assert os.path.isdir(tmp_path / "debugging")


class TestSchedules:
    def test_grad_accum_rescales_schedule(self):
        """With k-step accumulation the cosine must span train_steps/k
        optimizer updates, reaching end_value at the run's true end."""
        from tensorflow_examples_tpu.train.config import TrainConfig
        from tensorflow_examples_tpu.train.optimizers import warmup_cosine

        cfg = TrainConfig(
            train_steps=1000, warmup_steps=100, learning_rate=1.0,
            grad_accum_steps=4,
        )
        sched = warmup_cosine(cfg)
        # 1000 micro-steps = 250 updates; update 250 is the end.
        assert float(sched(250)) < 1e-6
        assert float(sched(25)) == pytest.approx(1.0)  # end of warmup
        # Without accumulation the same horizon is in raw steps.
        sched1 = warmup_cosine(cfg.replace(grad_accum_steps=1))
        assert float(sched1(1000)) < 1e-6
        assert float(sched1(100)) == pytest.approx(1.0)


class TestCollectivesFacade:
    """core/collectives.py (SURVEY.md §5h): the shard_map collective
    surface — semantics checked against numpy on an 8-device axis."""

    def test_psum_allgather_reducescatter_ppermute(self, devices):
        from jax.sharding import Mesh

        from tensorflow_examples_tpu.core import collectives as coll

        n = 8
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("x",))
        x = np.arange(n * 8, dtype=np.float32).reshape(n, 8)

        def f(v):
            v = v[0]  # local shard [8]
            return {
                "psum": coll.psum(v, "x"),
                "gather": coll.all_gather(v, "x"),
                "rs": coll.reduce_scatter(v, "x"),
                "hop": coll.ppermute(v, "x", coll.ring_perm(n)),
            }

        out = jax.jit(
            coll.shard_map(
                f,
                mesh=mesh,
                in_specs=P("x"),
                out_specs={
                    "psum": P(),
                    "gather": P(),
                    "rs": P("x"),
                    "hop": P("x"),
                },
                check_vma=False,
            )
        )(x)
        np.testing.assert_allclose(np.asarray(out["psum"]), x.sum(0))
        np.testing.assert_allclose(np.asarray(out["gather"]), x.reshape(-1))
        # reduce_scatter: every rank keeps 1/8 of the summed [8] vector;
        # out_specs P("x") re-assembles the shards back into the full sum.
        np.testing.assert_allclose(np.asarray(out["rs"]), x.sum(0))
        np.testing.assert_allclose(
            np.asarray(out["hop"]), np.roll(x, 1, axis=0).reshape(-1)
        )
