"""CIFAR-10 ResNet-20 integration tests: tiny end-to-end train on the
shared loop with BatchNorm state threading (SURVEY.md §4 integration tier).
"""

import numpy as np
import pytest

from tensorflow_examples_tpu.data.memory import eval_batches, train_iterator
from tensorflow_examples_tpu.data.sources import synthetic_images
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import cifar10


@pytest.fixture(scope="module")
def tiny_cfg(tmp_path_factory):
    return cifar10.Cifar10Config(
        global_batch_size=32,
        train_steps=12,
        warmup_steps=2,
        learning_rate=0.05,
        precision="f32",
        log_every=6,
        eval_every=0,
        checkpoint_every=0,
        workdir="",
        augment=True,
    )


@pytest.fixture(scope="module")
def tiny_ds():
    return synthetic_images(n=256, shape=(32, 32, 3), num_classes=10, seed=0)


def test_train_loss_decreases(tiny_cfg, tiny_ds):
    trainer = Trainer(cifar10.make_task(tiny_cfg), tiny_cfg)
    it = train_iterator(
        tiny_ds,
        tiny_cfg.global_batch_size,
        seed=0,
        augment=cifar10.train_augment(tiny_cfg),
    )
    first_loss = None
    state = trainer.state
    for i in range(tiny_cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        if first_loss is None:
            first_loss = float(m["loss"])
    assert float(m["loss"]) < first_loss


def test_batch_stats_are_threaded(tiny_cfg, tiny_ds):
    trainer = Trainer(cifar10.make_task(tiny_cfg), tiny_cfg)
    it = train_iterator(tiny_ds, tiny_cfg.global_batch_size, seed=0)
    before = np.asarray(
        trainer.state.model_state["batch_stats"]["stem_bn"]["mean"]
    )
    state, _ = trainer._train_step(trainer.state, trainer._put_batch(next(it)))
    after = np.asarray(state.model_state["batch_stats"]["stem_bn"]["mean"])
    assert not np.allclose(before, after)


def test_eval_runs_with_model_state(tiny_cfg, tiny_ds):
    trainer = Trainer(cifar10.make_task(tiny_cfg), tiny_cfg)
    metrics = trainer.evaluate(eval_batches(tiny_ds, 32))
    assert "accuracy" in metrics and "loss" in metrics
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_checkpoint_roundtrip_includes_model_state(tiny_ds, tmp_path):
    cfg = cifar10.Cifar10Config(
        global_batch_size=32,
        train_steps=3,
        warmup_steps=1,
        precision="f32",
        log_every=10**9,
        eval_every=0,
        checkpoint_every=3,
        workdir=str(tmp_path),
        augment=False,
    )
    trainer = Trainer(cifar10.make_task(cfg), cfg)
    trainer.fit(
        lambda start: train_iterator(
            tiny_ds, cfg.global_batch_size, seed=0, start_step=start
        )
    )
    stats = np.asarray(
        trainer.state.model_state["batch_stats"]["stem_bn"]["mean"]
    )

    trainer2 = Trainer(cifar10.make_task(cfg), cfg)
    from tensorflow_examples_tpu.train.checkpoint import CheckpointManager

    restored = CheckpointManager(str(tmp_path)).restore_latest(trainer2.state)
    assert restored is not None
    state2, step = restored
    assert step == 3
    np.testing.assert_allclose(
        np.asarray(state2.model_state["batch_stats"]["stem_bn"]["mean"]),
        stats,
        rtol=1e-6,
    )
