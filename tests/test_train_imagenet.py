"""ImageNet workload: synthetic smoke e2e + TFRecord pipeline unit tests."""

import numpy as np
import pytest

from tensorflow_examples_tpu.data import imagenet as imagenet_data
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import imagenet


def tiny_config(**kw):
    base = dict(
        image_size=32,
        num_classes=4,
        global_batch_size=16,
        train_steps=25,
        warmup_steps=5,
        learning_rate=0.01,
        log_every=10,
        eval_every=0,
        checkpoint_every=0,
        precision="f32",
        eval_batches=2,
    )
    base.update(kw)
    return imagenet.ImagenetConfig(**base)


def test_synthetic_smoke(mesh8):
    cfg = tiny_config()
    trainer = Trainer(imagenet.make_task(cfg), cfg, mesh=mesh8)
    it = imagenet.make_train_iter(cfg, 0)
    state = trainer.state
    losses = []
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        losses.append(float(m["loss"]))
    trainer.state = state
    assert np.all(np.isfinite(losses))
    # Synthetic stream is deliberately noisy; compare window means.
    early, late = np.mean(losses[:5]), np.mean(losses[-5:])
    assert late < early, f"no learning: {early} -> {late} ({losses})"
    metrics = trainer.evaluate(imagenet.make_eval_iter(cfg))
    assert "accuracy" in metrics and "top5_accuracy" in metrics
    assert 0.0 <= metrics["top5_accuracy"] <= 1.0


def _write_tfrecords(tf, tmp_path, split, n_shards=2, per_shard=3):
    rng = np.random.default_rng(0)
    labels = []
    for s in range(n_shards):
        path = str(tmp_path / f"{split}-{s:05d}-of-{n_shards:05d}")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_shard):
                img = rng.integers(0, 255, (48, 64, 3), np.uint8)
                label = int(rng.integers(1, 5))  # 1-based, ImageNet style
                labels.append(label)
                ex = tf.train.Example(
                    features=tf.train.Features(
                        feature={
                            "image/encoded": tf.train.Feature(
                                bytes_list=tf.train.BytesList(
                                    value=[tf.io.encode_jpeg(img).numpy()]
                                )
                            ),
                            "image/class/label": tf.train.Feature(
                                int64_list=tf.train.Int64List(value=[label])
                            ),
                        }
                    )
                )
                w.write(ex.SerializeToString())
    return labels


def test_tfrecord_pipeline(tmp_path):
    tf = pytest.importorskip("tensorflow")
    _write_tfrecords(tf, tmp_path, "train")
    _write_tfrecords(tf, tmp_path, "validation")
    assert imagenet_data.has_tfrecords(str(tmp_path), "train")

    it = imagenet_data.tfrecord_iter(
        str(tmp_path), "train", 4, train=True, image_size=32
    )
    b = next(it)
    assert b["image"].shape == (4, 32, 32, 3)
    assert b["image"].dtype == np.float32
    assert b["label"].min() >= 0 and b["label"].max() <= 3  # 1-based → 0-based

    # Eval: 6 examples at batch 4 → final batch padded with mask.
    batches = list(
        imagenet_data.tfrecord_iter(
            str(tmp_path), "validation", 4, train=False, image_size=32
        )
    )
    assert len(batches) == 2
    assert batches[0]["mask"].sum() == 4
    assert batches[1]["mask"].sum() == 2
    assert batches[1]["image"].shape == (4, 32, 32, 3)


def test_tfrecord_exact_resume(tmp_path):
    """VERDICT r2 item 5: exact resume on the STREAMING path. A resumed
    iterator (start_step=4) must replay the uninterrupted run's batches
    5… bit-exactly — shuffles, epoch boundaries, and random crop/flip
    augmentations all reproduced on TFRecord data."""
    tf = pytest.importorskip("tensorflow")
    _write_tfrecords(tf, tmp_path, "train", n_shards=2, per_shard=8)

    def take(start_step, n):
        it = imagenet_data.tfrecord_iter(
            str(tmp_path), "train", 4, train=True, image_size=32,
            seed=3, exact=True, start_step=start_step,
        )
        return [next(it) for _ in range(n)]

    # 8 steps × batch 4 = 32 records = 2 epochs of the 16-record set:
    # the comparison crosses an epoch boundary (reshuffle + re-augment).
    full = take(0, 8)
    resumed = take(4, 4)  # resume exactly at the epoch boundary
    for want, got in zip(full[4:], resumed):
        np.testing.assert_array_equal(want["label"], got["label"])
        np.testing.assert_array_equal(want["image"], got["image"])
    # Mid-epoch resumes: in-epoch record skip in epoch 0 and in epoch 1.
    for start in (2, 5):
        got = take(start, 2)
        for want, g in zip(full[start:], got):
            np.testing.assert_array_equal(want["label"], g["label"])
            np.testing.assert_array_equal(want["image"], g["image"])
    # Same seed, fresh run: reproducible from the top as well.
    again = take(0, 2)
    np.testing.assert_array_equal(full[0]["image"], again[0]["image"])
    # Augmentations really are live on this path (two records of the
    # same class differ unless crop/flip collapsed to identity).
    assert not np.array_equal(full[0]["image"], full[1]["image"])


def test_tfrecord_exact_resume_through_workload(tmp_path):
    """The workload plumbs (start_step, deterministic_input) into the
    pipeline — the path fit() uses when restoring a checkpoint."""
    tf = pytest.importorskip("tensorflow")
    _write_tfrecords(tf, tmp_path, "train", n_shards=2, per_shard=8)
    cfg = tiny_config(data_dir=str(tmp_path), global_batch_size=4)

    it0 = imagenet.make_train_iter(cfg, 0)
    full = [next(it0) for _ in range(5)]
    it4 = imagenet.make_train_iter(cfg, 4)
    got = next(it4)
    np.testing.assert_array_equal(full[4]["image"], got["image"])
    np.testing.assert_array_equal(full[4]["label"], got["label"])


def test_workload_routes_to_parallel_pipeline(tmp_path):
    """ISSUE 6 wiring: --input_workers>0 moves the TFRecord train path
    onto the sharded-reader + worker-pool pipeline (background-marked,
    closeable, deterministic across rebuilds), without touching the
    default (input_workers=0) tf.data path."""
    import threading

    from tensorflow_examples_tpu.data import sources as sources_mod

    rng = np.random.default_rng(0)

    def jpeg():
        import io

        from PIL import Image

        img = rng.integers(0, 255, (40, 48, 3), np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=85)
        return buf.getvalue()

    for s in range(2):
        sources_mod.write_tfrecord(
            str(tmp_path / f"train-{s:05d}-of-00002"),
            [
                sources_mod.make_example(
                    {"image/encoded": jpeg(), "image/class/label": 1 + s}
                )
                for _ in range(8)
            ],
        )
    cfg = tiny_config(
        data_dir=str(tmp_path), global_batch_size=4,
        input_workers=2, input_readers=2,
    )
    started = threading.active_count()
    it = imagenet.make_train_iter(cfg, 0)
    assert getattr(it, "background", False)  # prefetch records data_wait
    a = [next(it) for _ in range(3)]
    assert a[0]["image"].shape == (4, cfg.image_size, cfg.image_size, 3)
    it.close()
    it2 = imagenet.make_train_iter(cfg, 0)
    b = [next(it2) for _ in range(3)]
    it2.close()
    for want, got in zip(a, b):
        np.testing.assert_array_equal(want["image"], got["image"])
    deadline = __import__("time").time() + 5
    while (
        threading.active_count() > started
        and __import__("time").time() < deadline
    ):
        __import__("time").sleep(0.01)
    assert threading.active_count() <= started  # clean drain, no orphans


def test_synthetic_stream_determinism():
    a = next(imagenet_data.synthetic_train_iter(4, image_size=16, seed=7))
    b = next(imagenet_data.synthetic_train_iter(4, image_size=16, seed=7))
    np.testing.assert_array_equal(a["image"], b["image"])
    c = next(
        imagenet_data.synthetic_train_iter(4, image_size=16, seed=7, start_step=1)
    )
    assert not np.array_equal(a["image"], c["image"])
