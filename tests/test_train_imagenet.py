"""ImageNet workload: synthetic smoke e2e + TFRecord pipeline unit tests."""

import numpy as np
import pytest

from tensorflow_examples_tpu.data import imagenet as imagenet_data
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import imagenet


def tiny_config(**kw):
    base = dict(
        image_size=32,
        num_classes=4,
        global_batch_size=16,
        train_steps=25,
        warmup_steps=5,
        learning_rate=0.01,
        log_every=10,
        eval_every=0,
        checkpoint_every=0,
        precision="f32",
        eval_batches=2,
    )
    base.update(kw)
    return imagenet.ImagenetConfig(**base)


def test_synthetic_smoke(mesh8):
    cfg = tiny_config()
    trainer = Trainer(imagenet.make_task(cfg), cfg, mesh=mesh8)
    it = imagenet.make_train_iter(cfg, 0)
    state = trainer.state
    losses = []
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        losses.append(float(m["loss"]))
    trainer.state = state
    assert np.all(np.isfinite(losses))
    # Synthetic stream is deliberately noisy; compare window means.
    early, late = np.mean(losses[:5]), np.mean(losses[-5:])
    assert late < early, f"no learning: {early} -> {late} ({losses})"
    metrics = trainer.evaluate(imagenet.make_eval_iter(cfg))
    assert "accuracy" in metrics and "top5_accuracy" in metrics
    assert 0.0 <= metrics["top5_accuracy"] <= 1.0


def _write_tfrecords(tf, tmp_path, split, n_shards=2, per_shard=3):
    rng = np.random.default_rng(0)
    labels = []
    for s in range(n_shards):
        path = str(tmp_path / f"{split}-{s:05d}-of-{n_shards:05d}")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_shard):
                img = rng.integers(0, 255, (48, 64, 3), np.uint8)
                label = int(rng.integers(1, 5))  # 1-based, ImageNet style
                labels.append(label)
                ex = tf.train.Example(
                    features=tf.train.Features(
                        feature={
                            "image/encoded": tf.train.Feature(
                                bytes_list=tf.train.BytesList(
                                    value=[tf.io.encode_jpeg(img).numpy()]
                                )
                            ),
                            "image/class/label": tf.train.Feature(
                                int64_list=tf.train.Int64List(value=[label])
                            ),
                        }
                    )
                )
                w.write(ex.SerializeToString())
    return labels


def test_tfrecord_pipeline(tmp_path):
    tf = pytest.importorskip("tensorflow")
    _write_tfrecords(tf, tmp_path, "train")
    _write_tfrecords(tf, tmp_path, "validation")
    assert imagenet_data.has_tfrecords(str(tmp_path), "train")

    it = imagenet_data.tfrecord_iter(
        str(tmp_path), "train", 4, train=True, image_size=32
    )
    b = next(it)
    assert b["image"].shape == (4, 32, 32, 3)
    assert b["image"].dtype == np.float32
    assert b["label"].min() >= 0 and b["label"].max() <= 3  # 1-based → 0-based

    # Eval: 6 examples at batch 4 → final batch padded with mask.
    batches = list(
        imagenet_data.tfrecord_iter(
            str(tmp_path), "validation", 4, train=False, image_size=32
        )
    )
    assert len(batches) == 2
    assert batches[0]["mask"].sum() == 4
    assert batches[1]["mask"].sum() == 2
    assert batches[1]["image"].shape == (4, 32, 32, 3)


def test_synthetic_stream_determinism():
    a = next(imagenet_data.synthetic_train_iter(4, image_size=16, seed=7))
    b = next(imagenet_data.synthetic_train_iter(4, image_size=16, seed=7))
    np.testing.assert_array_equal(a["image"], b["image"])
    c = next(
        imagenet_data.synthetic_train_iter(4, image_size=16, seed=7, start_step=1)
    )
    assert not np.array_equal(a["image"], c["image"])
