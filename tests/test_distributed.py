"""Multi-host (multi-process) integration: the real `jax.distributed` path.

The reference's multi-worker story was TF_CONFIG + gRPC bootstrap
(SURVEY.md §3(5)); ours is core/distributed.initialize →
jax.distributed.initialize. This test actually spawns TWO processes,
forms a mesh spanning them (1 CPU device each), and runs the shared
Trainer for a few MNIST steps — the gradient all-reduce crosses the
process boundary. Losses must match bit-for-bit across ranks (global
batch semantics) and decrease.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest


def _worker_env():
    """Each worker gets ONE cpu device: strip the fake-device flag the
    test harness (conftest) sets for the parent process. Also drop
    PALLAS_AXON_POOL_IPS: the session sitecustomize's axon register()
    call can block interpreter START >=90 s whenever the TPU tunnel
    endpoint is wedged — a pure-CPU worker must never pay that."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    return env

_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from tensorflow_examples_tpu.core import distributed

    rank = int(sys.argv[1])
    distributed.initialize(
        coordinator_address=sys.argv[2], num_processes=2, process_id=rank
    )
    assert jax.device_count() == 2, jax.device_count()
    assert jax.process_count() == 2

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    cfg = mnist.MnistConfig(
        global_batch_size=16, train_steps=10, hidden=32, num_layers=1,
        precision="f32", log_every=10**9, checkpoint_every=0,
        watchdog_secs=0,
    )
    mesh = create_mesh(MeshConfig(data=2))
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=mesh)
    ds = synthetic_images(n=128, shape=(28, 28, 1), num_classes=10, seed=0)
    # Same seed on every host -> identical global batches; device_put
    # slices out each process's shard (global-view semantics).
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    state = trainer.state
    losses = []
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        losses.append(float(m["loss"]))
    print("LOSSES", rank, " ".join(f"{l:.6f}" for l in losses), flush=True)
    """
)


_EVAL_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from tensorflow_examples_tpu.core import distributed

    rank = int(sys.argv[1])
    distributed.initialize(
        coordinator_address=sys.argv[2], num_processes=2, process_id=rank
    )

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import InMemoryDataset, eval_batches
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    cfg = mnist.MnistConfig(
        global_batch_size=16, hidden=32, num_layers=1, precision="f32",
        log_every=10**9, checkpoint_every=0, watchdog_secs=0,
    )
    mesh = create_mesh(MeshConfig(data=2))
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=mesh)
    ds = synthetic_images(n=64, shape=(28, 28, 1), num_classes=10, seed=7)
    # Disjoint, DIFFERENTLY-SIZED per-host shards: rank0 evaluates 40
    # examples (5 local batches of 8), rank1 evaluates 24 (3 batches) —
    # exercising the zero-weight padding that equalizes host streams.
    lo, hi = (0, 40) if rank == 0 else (40, 64)
    local = InMemoryDataset({k: v[lo:hi] for k, v in ds.arrays.items()})
    m = trainer.evaluate(eval_batches(local, cfg.global_batch_size // 2))
    print(f"EVAL {rank} {m['accuracy']:.8f} {m['loss']:.8f}", flush=True)
    """
)


@pytest.mark.timeout(180)
def test_two_process_eval_merges_host_shards():
    """evaluate() over differing per-host shards == the single-process
    value over the union (VERDICT r1: multi-host eval was unproven)."""
    import jax

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import eval_batches
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    outs = _run_workers(_EVAL_WORKER)
    got = {}
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("EVAL")][0]
        _, rank, acc, loss = line.split()
        got[int(rank)] = (float(acc), float(loss))
    assert set(got) == {0, 1}
    assert got[0] == got[1], got  # both hosts see the merged metric

    # Single-process reference over the union of both hosts' shards,
    # identical params (same seed, same deterministic jit-init).
    cfg = mnist.MnistConfig(
        global_batch_size=16, hidden=32, num_layers=1, precision="f32",
        log_every=10**9, checkpoint_every=0, watchdog_secs=0,
    )
    mesh = create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=mesh)
    ds = synthetic_images(n=64, shape=(28, 28, 1), num_classes=10, seed=7)
    ref = trainer.evaluate(eval_batches(ds, 16))
    assert abs(got[0][0] - ref["accuracy"]) < 1e-6, (got[0], ref)
    assert abs(got[0][1] - ref["loss"]) < 1e-5, (got[0], ref)


def _run_workers(worker_src, env=None, timeout=150, extra=()):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src, str(r), addr, *extra],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env if env is not None else _worker_env(),
        )
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:  # never orphan a peer blocked in a collective
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
    return outs


_TP_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from tensorflow_examples_tpu.core import distributed

    rank = int(sys.argv[1])
    distributed.initialize(
        coordinator_address=sys.argv[2], num_processes=2, process_id=rank
    )
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    cfg = gpt2.Gpt2Config(
        vocab_size=64, seq_len=16, num_layers=2, num_heads=4, d_model=32,
        dropout=0.0, attention="xla", global_batch_size=16, train_steps=6,
        warmup_steps=2, precision="f32", log_every=10**9,
        checkpoint_every=0, watchdog_secs=0,
    )
    # data axis spans the two PROCESSES (jax.devices() orders by
    # process), model axis spans each process's 4 local devices: the
    # Megatron TP collectives stay within-host, the DP gradient
    # all-reduce crosses the process boundary.
    mesh = create_mesh(MeshConfig(data=2, model=4))
    trainer = Trainer(gpt2.make_task(cfg, mesh), cfg, mesh=mesh)
    ds, _ = gpt2.datasets(cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    state = trainer.state
    losses = []
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        losses.append(float(m["loss"]))
    print("LOSSES", rank, " ".join(f"{l:.6f}" for l in losses), flush=True)
    """
)


@pytest.mark.timeout(420)
def test_two_process_tp_matches_single_process():
    """Multi-host beyond DP (VERDICT r3 item 6): a dp2×model4 mesh
    spanning two processes (model within each host's 4 devices, data
    across hosts) must reproduce the single-process loss curve of the
    same global mesh — the TP psums run within-host, the DP gradient
    reduction crosses the process boundary."""
    import jax

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    env = _worker_env()
    env["XLA_FLAGS"] = (
        env["XLA_FLAGS"] + " --xla_force_host_platform_device_count=4"
    ).strip()
    outs = _run_workers(_TP_WORKER, env=env, timeout=360)
    losses = {}
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("LOSSES")][0]
        parts = line.split()
        losses[int(parts[1])] = [float(x) for x in parts[2:]]
    assert set(losses) == {0, 1}
    assert losses[0] == losses[1], losses  # identical on both ranks

    # Single-process reference: same global mesh shape over this
    # process's 8 virtual devices, same seed → same data, same init.
    cfg = gpt2.Gpt2Config(
        vocab_size=64, seq_len=16, num_layers=2, num_heads=4, d_model=32,
        dropout=0.0, attention="xla", global_batch_size=16, train_steps=6,
        warmup_steps=2, precision="f32", log_every=10**9,
        checkpoint_every=0, watchdog_secs=0,
    )
    mesh = create_mesh(MeshConfig(data=2, model=4))
    trainer = Trainer(gpt2.make_task(cfg, mesh), cfg, mesh=mesh)
    ds, _ = gpt2.datasets(cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    state = trainer.state
    ref = []
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        ref.append(float(m["loss"]))
    np.testing.assert_allclose(losses[0], ref, rtol=2e-5, atol=1e-6)


_LOCAL_BATCH_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from tensorflow_examples_tpu.core import distributed

    rank = int(sys.argv[1])
    distributed.initialize(
        coordinator_address=sys.argv[2], num_processes=2, process_id=rank
    )

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    cfg = mnist.MnistConfig(
        global_batch_size=16, train_steps=6, hidden=32, num_layers=1,
        precision="f32", log_every=6, checkpoint_every=0, watchdog_secs=0,
        steps_per_launch=2,
    )
    mesh = create_mesh(MeshConfig(data=2))
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=mesh)
    ds = synthetic_images(n=128, shape=(28, 28, 1), num_classes=10, seed=0)

    def local_iter(start_step):
        # PER-HOST semantics: each process yields only ITS half of every
        # global batch (rank 0 rows 0-7, rank 1 rows 8-15), as a per-host
        # TFRecord shard reader would; put_local_batch assembles the
        # global [16, ...] array (stacked [2, 16, ...] under bundling).
        rows = cfg.global_batch_size // 2
        for b in train_iterator(ds, cfg.global_batch_size, seed=0):
            yield {k: v[rank * rows : (rank + 1) * rows] for k, v in b.items()}

    m = trainer.fit(
        local_iter, num_steps=cfg.train_steps, local_batches=True
    )
    print(f"FINAL {rank} {m['loss']:.8f} {m['accuracy']:.8f}", flush=True)
    """
)


@pytest.mark.timeout(300)
def test_two_process_local_batches_bundled_matches_global():
    """The per-host input path (fit(local_batches=True) →
    put_local_batch / make_array_from_process_local_data), COMBINED
    with steps_per_launch bundling: two processes each feeding disjoint
    halves of every global batch must reproduce the single-process
    global-view run on the same mesh shape — same data, same program,
    same window-mean metrics."""
    import jax

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    outs = _run_workers(_LOCAL_BATCH_WORKER, timeout=270)
    got = {}
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("FINAL")][0]
        _, rank, loss, acc = line.split()
        got[int(rank)] = (float(loss), float(acc))
    assert set(got) == {0, 1}
    assert got[0] == got[1], got  # identical merged metrics on both ranks

    # Single-process global-view reference: same data=2 mesh shape over
    # two of this process's fake devices, same bundled config, the SAME
    # global batches fed whole.
    cfg = mnist.MnistConfig(
        global_batch_size=16, train_steps=6, hidden=32, num_layers=1,
        precision="f32", log_every=6, checkpoint_every=0, watchdog_secs=0,
        steps_per_launch=2,
    )
    mesh = create_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=mesh)
    ds = synthetic_images(n=128, shape=(28, 28, 1), num_classes=10, seed=0)
    ref = trainer.fit(
        train_iterator(ds, cfg.global_batch_size, seed=0),
        num_steps=cfg.train_steps,
    )
    assert abs(got[0][0] - ref["loss"]) < 1e-5, (got[0], ref["loss"])
    assert abs(got[0][1] - ref["accuracy"]) < 1e-6, (got[0], ref["accuracy"])


_FLEET_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from tensorflow_examples_tpu.core import distributed

    rank = int(sys.argv[1])
    distributed.initialize(
        coordinator_address=sys.argv[2], num_processes=2, process_id=rank
    )

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.utils import faults as faults_mod
    from tensorflow_examples_tpu.workloads import mnist

    workdir = sys.argv[3]
    cfg = mnist.MnistConfig(
        global_batch_size=16, train_steps=8, hidden=32, num_layers=1,
        precision="f32", log_every=4, checkpoint_every=0, resume=False,
        watchdog_secs=0, bad_step_policy="off", workdir=workdir,
        telemetry_sinks="jsonl", telemetry_trace=False,
        straggler_skew_factor=2.0,
    )
    if rank == 1:
        # The injected straggler: two slow input fetches on host 1 only
        # (utils/faults.py slow-host spec) — an INPUT-side skew.
        faults_mod.install("slow@5:1.5,slow@6:1.5")
    mesh = create_mesh(MeshConfig(data=2))
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=mesh)
    ds = synthetic_images(n=128, shape=(28, 28, 1), num_classes=10, seed=0)
    m = trainer.fit(
        lambda start: train_iterator(ds, 16, seed=0, start_step=start),
        num_steps=cfg.train_steps,
    )
    print(f"FINAL {rank} {m['loss']:.6f}", flush=True)
    """
)


@pytest.mark.timeout(300)
@pytest.mark.telemetry
def test_two_process_fleet_line_names_injected_straggler(tmp_path):
    """ISSUE 4 acceptance: a REAL 2-process run with a fault-injected
    slow host must (a) write one telemetry shard per host, (b) emit
    kind="fleet" lines whose last summary names host 1 as an input-side
    straggler past the skew threshold, (c) log the straggler warning on
    host 0, and (d) feed the shard-merging report CLI, which flags the
    slowest host."""
    import json

    workdir = str(tmp_path)
    try:
        outs = _run_workers(_FLEET_WORKER, timeout=270, extra=(workdir,))
    except AssertionError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            # This jax build can't run collectives across CPU processes
            # (the same limitation fails every 2-process test here); the
            # mocked-allgather acceptance path is pinned CPU-green in
            # tests/test_telemetry.py.
            pytest.skip("no multiprocess CPU collectives in this jax build")
        raise
    assert any("FINAL 0" in o for o in outs)

    tdir = os.path.join(workdir, "telemetry")
    shard1 = os.path.join(tdir, "telemetry.host1.jsonl")
    assert os.path.isfile(shard1)
    # Process 0 writes NO shard: metrics.jsonl already is its stream
    # (the report merges it in as host 0).
    assert not os.path.isfile(os.path.join(tdir, "telemetry.host0.jsonl"))

    from tensorflow_examples_tpu.telemetry import schema

    with open(os.path.join(tdir, "metrics.jsonl")) as f:
        lines = [json.loads(line) for line in f]
    for line in lines:
        assert schema.validate_line(line) == [], line
    assert all(line["host"] == 0 for line in lines)
    with open(shard1) as f:
        assert all(
            json.loads(line)["host"] == 1 for line in f if line.strip()
        )

    fleets = [l for l in lines if l["kind"] == "fleet"]
    assert fleets, [l["kind"] for l in lines]
    fl = fleets[-1]["fleet"]
    assert [h["host"] for h in fl["hosts"]] == [0, 1]
    assert fl["slowest_host"] == 1
    assert fl["straggler"] is True
    assert fl["side"] == "input"
    assert fl["skew"] >= 2.0
    # host 1's own numbers carry the stall; host 0 stayed fast
    assert fl["hosts"][1]["data_fetch_p95"] > 1.0
    assert fl["hosts"][1]["step_time_p95"] > fl["hosts"][0]["step_time_p95"]

    # The straggler warning names the host and the side (host 0 logs it).
    rank0_out = [o for o in outs if "FINAL 0" in o][0]
    assert "FLEET STRAGGLER" in rank0_out
    assert "host 1" in rank0_out and "input-side" in rank0_out

    # Shard-merging report satellite, on the real multi-host artifacts.
    report = subprocess.run(
        [
            sys.executable,
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools",
                "telemetry_report.py",
            ),
            workdir,
            "--json",
            "-",
        ],
        capture_output=True,
        text=True,
        timeout=120,
        env=_worker_env(),
    )
    assert report.returncode == 0, report.stderr + report.stdout
    assert "2 host shard(s)" in report.stdout
    assert "SLOWEST host 1" in report.stdout
    rec = json.loads(report.stdout[report.stdout.index("{"):])
    assert [h["host"] for h in rec["hosts"]] == [0, 1]
    assert rec["slowest_host"] == 1
    assert rec["fleet"]["slowest_host"] == 1
    assert rec["fleet_straggler_windows"] >= 1


@pytest.mark.timeout(180)
def test_two_process_training():
    outs = _run_workers(_WORKER)
    losses = {}
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("LOSSES")][0]
        parts = line.split()
        losses[int(parts[1])] = [float(x) for x in parts[2:]]
    assert set(losses) == {0, 1}
    # Bit-identical across ranks (same global program, same data).
    assert losses[0] == losses[1], losses
    assert np.all(np.isfinite(losses[0]))
    assert np.mean(losses[0][-3:]) < np.mean(losses[0][:3]), losses[0]
