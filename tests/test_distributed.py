"""Multi-host (multi-process) integration: the real `jax.distributed` path.

The reference's multi-worker story was TF_CONFIG + gRPC bootstrap
(SURVEY.md §3(5)); ours is core/distributed.initialize →
jax.distributed.initialize. This test actually spawns TWO processes,
forms a mesh spanning them (1 CPU device each), and runs the shared
Trainer for a few MNIST steps — the gradient all-reduce crosses the
process boundary. Losses must match bit-for-bit across ranks (global
batch semantics) and decrease.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest


def _worker_env():
    """Each worker gets ONE cpu device: strip the fake-device flag the
    test harness (conftest) sets for the parent process."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    return env

_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from tensorflow_examples_tpu.core import distributed

    rank = int(sys.argv[1])
    distributed.initialize(
        coordinator_address=sys.argv[2], num_processes=2, process_id=rank
    )
    assert jax.device_count() == 2, jax.device_count()
    assert jax.process_count() == 2

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    cfg = mnist.MnistConfig(
        global_batch_size=16, train_steps=10, hidden=32, num_layers=1,
        precision="f32", log_every=10**9, checkpoint_every=0,
        watchdog_secs=0,
    )
    mesh = create_mesh(MeshConfig(data=2))
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=mesh)
    ds = synthetic_images(n=128, shape=(28, 28, 1), num_classes=10, seed=0)
    # Same seed on every host -> identical global batches; device_put
    # slices out each process's shard (global-view semantics).
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    state = trainer.state
    losses = []
    for _ in range(cfg.train_steps):
        state, m = trainer._train_step(state, trainer._put_batch(next(it)))
        losses.append(float(m["loss"]))
    print("LOSSES", rank, " ".join(f"{l:.6f}" for l in losses), flush=True)
    """
)


@pytest.mark.timeout(180)
def test_two_process_training():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(r), addr],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=_worker_env(),
        )
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=150)
            outs.append(out)
    finally:
        for p in procs:  # never orphan a peer blocked in a collective
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"

    losses = {}
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("LOSSES")][0]
        parts = line.split()
        losses[int(parts[1])] = [float(x) for x in parts[2:]]
    assert set(losses) == {0, 1}
    # Bit-identical across ranks (same global program, same data).
    assert losses[0] == losses[1], losses
    assert np.all(np.isfinite(losses[0]))
    assert np.mean(losses[0][-3:]) < np.mean(losses[0][:3]), losses[0]
